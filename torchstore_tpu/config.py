"""Central configuration object.

The reference configures itself through ~12 scattered env vars (SURVEY §5,
"config/flag system"; an author comment at
/root/reference/torchstore/transport/torchcomms/buffer.py:30-33 wishes for
strategy-level config). This build provides a real config object from day
one: every knob lives on ``StoreConfig``, env vars are read once as defaults,
and user code can override programmatically via ``initialize(config=...)``.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field, replace
from typing import Optional


@dataclass(frozen=True)
class EnvVar:
    """One operator knob: the single source of truth the env-registry lint
    (torchstore_tpu/analysis/checkers/env_registry.py) and the generated
    docs/API.md table are derived from. ``default=None`` means unset /
    computed dynamically (the doc string says how)."""

    name: str
    type: str  # "bool" | "int" | "float" | "str" | "path"
    default: object
    doc: str


# Every TORCHSTORE_TPU_* variable the tree reads. Adding a read site without
# an entry here fails `python scripts/tslint.py` (env-registry rule); after
# editing, regenerate the docs table with `scripts/tslint.py --regen-env-docs`.
ENV_REGISTRY: tuple[EnvVar, ...] = (
    # --- transports ---------------------------------------------------------
    EnvVar("TORCHSTORE_TPU_SHM_ENABLED", "bool", True,
           "Enable the shared-memory transport rung (same-host transfers "
           "through /dev/shm segments)."),
    EnvVar("TORCHSTORE_TPU_BULK_TCP_ENABLED", "bool", True,
           "Enable the bulk TCP transport rung (cross-host striped "
           "transfers over DCN)."),
    EnvVar("TORCHSTORE_TPU_ICI_ENABLED", "bool", True,
           "Enable the device (ICI) transfer rung for on-device arrays."),
    EnvVar("TORCHSTORE_TPU_ZERO_COPY_GET", "bool", True,
           "Same-host gets without an in-place destination return read-only "
           "snapshot views of SHM segments instead of copies."),
    EnvVar("TORCHSTORE_TPU_SHM_POOL_MAX_BYTES", "int", None,
           "Cap on the volume-side recycled SHM segment pool, bytes. "
           "Default: a quarter of /dev/shm's available space at startup, "
           "clamped to [4 GB, 64 GB]."),
    EnvVar("TORCHSTORE_TPU_USE_NATIVE", "bool", True,
           "Use the native C++ data-path library (libtsnative) when built."),
    # --- steady-state sync pipeline -----------------------------------------
    EnvVar("TORCHSTORE_TPU_LANDING_THREADS", "int", 0,
           "Size of the shared landing-copy thread pool that overlaps "
           "per-request segment copies with the event loop (0 = auto: one "
           "per core, capped at 4 — fast_copy is already internally "
           "threaded for large arrays, so the pool budgets against cores)."),
    EnvVar("TORCHSTORE_TPU_ARENA_MAX_BYTES", "int", 262144,
           "Tensors at or below this many bytes are packed into one shared "
           "arena segment per put batch (one handshake entry + one "
           "volume-side index pass instead of per-key segments); the bulk "
           "transport packs the same set into a single framed payload. "
           "0 disables packing."),
    EnvVar("TORCHSTORE_TPU_TRANSFER_QUANT", "str", "none",
           "Default wire quantization for state-dict publishes "
           "(none|int8|int8_block|int4_block): floating leaves ship as "
           "fused blockwise blobs (packed codes + f32 scale table in the "
           "SAME arena segment) instead of full-precision tensors. An "
           "explicit transfer_quant/transfer_dtype argument overrides "
           "this default per call."),
    EnvVar("TORCHSTORE_TPU_TRANSFER_QUANT_BLOCK", "int", 256,
           "Elements per quantization block for the blockwise modes "
           "(finer blocks: better accuracy, proportionally more scale "
           "bytes — 256 costs ~1.6% overhead at int8). Must be even for "
           "int4_block. Part of the plan signature: changing it is a "
           "restructure."),
    EnvVar("TORCHSTORE_TPU_DELTA_KEYFRAME", "int", 8,
           "Delta wire tier (WeightPublisher delta publishes): a full "
           "keyframe ships every this-many versions per key, bounding the "
           "chain a joining/lagging reader must walk. The publisher "
           "enforces channel keep >= this cadence so the chain is always "
           "retained."),
    EnvVar("TORCHSTORE_TPU_DELTA_SKIP_EPS", "float", 0.0,
           "Delta wire tier: extra absolute slack on the per-block skip "
           "threshold. A block skips (ships nothing) when its residual "
           "max|w_t - baseline| is at or below half the block's keyframe "
           "scale step (the representation's own noise floor) plus this "
           "slack. Residuals are measured against the live weights, so "
           "skipped error never compounds: served weights stay within "
           "~half a keyframe step of the true ones at every version."),
    EnvVar("TORCHSTORE_TPU_PLAN_CACHE", "bool", True,
           "Cache put/get_state_dict transfer plans per (store, size "
           "signature), invalidated by the controller's placement epoch, "
           "so repeated RL-sync iterations skip re-validation and "
           "re-locate."),
    EnvVar("TORCHSTORE_TPU_STREAM_POLL_S", "float", 10.0,
           "Layer-streamed sync: per-round long-poll window, seconds, for "
           "wait_for_stream on the controller (the acquire side re-polls "
           "after each window to refresh its lag gauge and deadline; "
           "wakeups are notify-driven, never a spin)."),
    EnvVar("TORCHSTORE_TPU_STREAM_RETRIES", "int", 2,
           "Layer-streamed sync: how many times a streamed acquire "
           "restarts after observing a superseded or mixed-generation "
           "stream (a newer publish overwrote keys mid-acquire) before "
           "failing loudly."),
    EnvVar("TORCHSTORE_TPU_BULK_EMULATE_GBPS", "float", 0,
           "Bench/test DCN emulation: when > 0, every bulk payload frame "
           "send adds the wall time a link of this bandwidth (GB/s) would "
           "need on top of the real transfer, so single-host benches "
           "measure the cross-host regime the bulk transport targets "
           "(bench.py delta_sync uses it). 0 (production default) "
           "disables pacing entirely."),
    EnvVar("TORCHSTORE_TPU_PUSH_SESSIONS", "bool", True,
           "Push-on-publish bulk sessions: a client that caches a "
           "doorbell plan also registers a persistent push subscription; "
           "the volume then streams freshly committed layers into the "
           "client's staging arena AT WATERMARK TIME, so the next warm "
           "get's first byte is a local memcpy (validated against the "
           "mirrored write generations before serving). Unsubscribed or "
           "lagging sessions fall back loudly to the doorbell ring."),
    EnvVar("TORCHSTORE_TPU_PUSH_STAGING_MAX_BYTES", "int", 1073741824,
           "Per-client cap on push-staged arena bytes; staging past the "
           "cap evicts oldest-staged plans first, and a single frame "
           "larger than the cap is never staged (its reads stay on the "
           "doorbell ring). Floor: 1 MiB."),
    EnvVar("TORCHSTORE_TPU_BULK_STRIPE_THRESHOLD", "int", 67108864,
           "Bulk transport payloads above this many bytes are striped "
           "across the pre-opened stripe connection set (puts, get "
           "replies, and IDX_PACKED doorbell replies)."),
    EnvVar("TORCHSTORE_TPU_RELAY_ENABLED", "bool", True,
           "Broadcast weight distribution: allow relay-tree fan-out of "
           "weight_channel versions (controller-driven volume-to-volume "
           "forwarding; subscribers opt in per channel via "
           "WeightSubscriber(relay=True) / client.relay_subscribe). "
           "0 disables relay subscription fleet-wide: acquires fall back "
           "to point-to-point reads from the origin volumes."),
    EnvVar("TORCHSTORE_TPU_RELAY_FANOUT", "int", 2,
           "Interior out-degree of the relay tree each published version "
           "flows down. The root (origin volume) always forwards to "
           "exactly ONE child so trainer-host egress stays O(1) however "
           "many fleets subscribe; 1 makes the whole tree a chain."),
    EnvVar("TORCHSTORE_TPU_RELAY_REPARENT_TIMEOUT_S", "float", 5.0,
           "How long a relay edge keeps retrying a failing parent before "
           "the controller re-parents the orphaned subtree onto the "
           "nearest healthy ancestor (the health supervisor's quarantine "
           "re-parents immediately, independent of this window)."),
    EnvVar("TORCHSTORE_TPU_ONE_SIDED", "bool", True,
           "One-sided data plane for warm gets: same-host readers with a "
           "cached plan read stamped (seqlock-validated) bytes directly "
           "from pre-attached SHM segments with zero RPCs; cross-host "
           "readers ring a bulk doorbell frame against a volume-cached "
           "get plan instead of issuing the get RPC. Torn/stale reads "
           "fall back loudly to the RPC path."),
    # --- scale-out metadata plane (torchstore_tpu/metadata/) ----------------
    EnvVar("TORCHSTORE_TPU_CONTROLLER_SHARDS", "int", 1,
           "Partition the controller's key->volume index across this many "
           "ControllerShard actors by stable key hash (1 = the classic "
           "single controller). Fleet-scoped state (placement epoch, "
           "health, streams, relay, leases) stays on the coordinator; "
           "clients fan batched metadata ops out per shard. An explicit "
           "ts.initialize(controller_shards=) overrides this default."),
    EnvVar("TORCHSTORE_TPU_META_STAMPED", "bool", True,
           "One-sided metadata reads: every index host publishes its "
           "committed index (and the coordinator its stream watermarks + "
           "placement epoch) into seqlock-stamped shm segments, so "
           "same-host clients resolve locations, validate cached plans, "
           "and poll streamed publishes with ZERO controller RPCs. "
           "Torn/stale reads fall back loudly to the RPC path."),
    EnvVar("TORCHSTORE_TPU_META_PUBLISH_MS", "float", 10,
           "Debounce interval for stamped metadata publishes, "
           "milliseconds: index/stream changes coalesce to at most one "
           "segment rewrite per interval (staleness is bounded by it; "
           "readers under-see progress, never the reverse)."),
    EnvVar("TORCHSTORE_TPU_META_SEGMENT_BYTES", "int", 8388608,
           "Size of each stamped metadata segment. A pickled view that "
           "outgrows it tombstones the segment (readers fall back to "
           "RPCs, loudly) rather than growing under attached readers."),
    EnvVar("TORCHSTORE_TPU_META_MIRROR", "bool", True,
           "Cross-host metadata mirroring: the coordinator runs a "
           "metadata feed that pushes the stamped segment images over "
           "persistent subscriptions (fanned through a relay tree, so "
           "index-host egress stays O(1) in subscriber count); each "
           "remote host's MetadataMirror republishes them into LOCAL shm "
           "replicas, extending the zero-RPC warm metadata paths across "
           "hosts. Off: remote clients use the RPC metadata plane only."),
    EnvVar("TORCHSTORE_TPU_META_MIRROR_INTERVAL_MS", "float", 20,
           "Feed pump poll interval, milliseconds: how often the root "
           "feed re-reads the local stamped segments and pushes changed "
           "images to subscribers (bounds mirror replica staleness "
           "alongside the publish debounce)."),
    EnvVar("TORCHSTORE_TPU_META_MIRROR_HEARTBEAT_S", "float", 0.2,
           "Feed heartbeat period, seconds: subscribers receive at least "
           "one frame per period even when no image changed, so a quiet "
           "feed is distinguishable from a dead parent."),
    EnvVar("TORCHSTORE_TPU_META_MIRROR_LAG_S", "float", 1.5,
           "Mirror staleness bound, seconds: a replica whose feed has "
           "been silent longer than this reports unfresh — every stamped "
           "read on that host falls back LOUDLY to the RPC plane "
           "(reason=mirror_lag) and the subscription re-parents around "
           "the dead feed (the down-set re-subscribe)."),
    # --- tiered capacity & multi-version serving (torchstore_tpu/tiering) ---
    EnvVar("TORCHSTORE_TPU_TIER_ENABLED", "bool", False,
           "Enable the disk spill tier: per-volume spill writers demote "
           "cold version groups from the memory/tmpfs tier to disk under "
           "the watermark policy, and gets on spilled keys fault back in "
           "through the normal transport ladder. Off: the store is "
           "memory-capacity-bound exactly as before (warm path pays one "
           "attribute check)."),
    EnvVar("TORCHSTORE_TPU_TIER_DIR", "path", None,
           "Root directory for the disk spill tier (one subdirectory per "
           "volume id). Default: <tmpdir>/torchstore_tpu_tier. Spill "
           "writes are crash-safe (write-temp, fsync, rename)."),
    EnvVar("TORCHSTORE_TPU_TIER_BUDGET_BYTES", "int", None,
           "Memory-tier pool budget, bytes, the spill watermarks apply "
           "to. Default: the SHM pool cap "
           "(TORCHSTORE_TPU_SHM_POOL_MAX_BYTES or its derived default)."),
    EnvVar("TORCHSTORE_TPU_TIER_HIGH_PCT", "float", 0.85,
           "Spill HIGH watermark: a volume whose resident bytes exceed "
           "this fraction of the pool budget starts demoting cold "
           "version groups (LRU by access; leased versions exempt)."),
    EnvVar("TORCHSTORE_TPU_TIER_LOW_PCT", "float", 0.65,
           "Spill LOW watermark: demotion stops once resident bytes drop "
           "under this fraction of the pool budget."),
    EnvVar("TORCHSTORE_TPU_TIER_SWEEP_INTERVAL_S", "float", 2.0,
           "Controller tier-sweep period, seconds: every interval the "
           "controller runs each volume's spill pass with the current "
           "lease pins and folds tier transitions into the index. <= 0 "
           "disables the background sweeper (ts.tier_sweep() still runs "
           "one on demand)."),
    EnvVar("TORCHSTORE_TPU_LEASE_TTL_S", "float", 30.0,
           "Default TTL, seconds, for cohort retention leases "
           "(lease_acquire without an explicit ttl_s; renew to keep a "
           "version pinned past it). A crashed cohort's pin expires "
           "instead of retaining capacity forever."),
    # --- control plane (torchstore_tpu/control/) ----------------------------
    EnvVar("TORCHSTORE_TPU_CONTROL_INTERVAL_S", "float", 0,
           "Placement policy engine reconcile period, seconds: every "
           "interval the controller snapshots fleet telemetry, runs the "
           "pure solver, and applies/audits the resulting actions "
           "(migrations, hot-key splits, relay re-ordering, frequency-"
           "aware demotions). <= 0 (the default) disables the periodic "
           "loop; ts.rebalance() / ts.control_plan() still serve on "
           "demand."),
    EnvVar("TORCHSTORE_TPU_CONTROL_OVERLOAD_RATIO", "float", 2.0,
           "Solver: a volume whose rolling-window traffic exceeds this "
           "multiple of the fleet mean counts as overloaded and sheds "
           "keys (migrations stop once it projects under the settle "
           "ratio)."),
    EnvVar("TORCHSTORE_TPU_CONTROL_MIN_WINDOW_BYTES", "int", 65536,
           "Solver: volumes whose rolling window moved fewer than this "
           "many bytes are ignored entirely — an idle fleet must plan "
           "zero actions."),
    EnvVar("TORCHSTORE_TPU_CONTROL_HOT_KEY_MIN_BYTES", "int", 1048576,
           "Solver: a key must move at least this many bytes in the "
           "window before it is hot enough to split across an additional "
           "replica."),
    EnvVar("TORCHSTORE_TPU_CONTROL_MIN_EDGE_BYTES", "int", 1048576,
           "Solver: relay trees re-order members by measured edge "
           "proximity only when the dominant consumer edge carried at "
           "least this many bytes."),
    EnvVar("TORCHSTORE_TPU_CONTROL_COOLDOWN_S", "float", 30.0,
           "Solver hysteresis: a subject acted on (or attempted) within "
           "this window is not acted on again, and a reversal of a prior "
           "action is damped for twice the window — the engine must "
           "converge, not oscillate."),
    EnvVar("TORCHSTORE_TPU_CONTROL_MAX_ACTIONS", "int", 8,
           "Solver: cap on actions per reconcile round (highest-impact "
           "first); convergence happens over rounds, not in one "
           "stop-the-world batch."),
    EnvVar("TORCHSTORE_TPU_CONTROL_ADMISSION", "bool", False,
           "Per-tenant admission control: client put/get batches reserve "
           "a token per logical op from a tenant-labeled bucket and "
           "sleep out any deficit BEFORE touching a volume. The refill "
           "rate scales down while overload signals (per-shard metadata "
           "RPC inflight, per-volume landing_inflight) exceed "
           "TORCHSTORE_TPU_CONTROL_OVERLOAD_INFLIGHT."),
    EnvVar("TORCHSTORE_TPU_CONTROL_ADMIT_RATE", "float", 512.0,
           "Admission control: steady-state refill rate, logical ops per "
           "second per client."),
    EnvVar("TORCHSTORE_TPU_CONTROL_ADMIT_BURST", "float", None,
           "Admission control: bucket depth, ops (how far a tenant may "
           "burst above the steady rate before queuing at its own "
           "bucket). Default: 2x the admit rate."),
    EnvVar("TORCHSTORE_TPU_CONTROL_OVERLOAD_INFLIGHT", "int", 16,
           "Admission control: overload knee. While the deepest observed "
           "inflight signal exceeds this, the refill factor scales down "
           "proportionally (knee/depth, floored at 0.1); throttle "
           "engage/release transitions are recorded as flight-recorder "
           "decision events."),
    EnvVar("TORCHSTORE_TPU_CONTROL_REPLICA_SPREAD", "bool", False,
           "Hot-key read spreading: clients rotate which replica they "
           "read first by a stable per-client salt instead of every "
           "client draining the same deterministic first choice — the "
           "read-side half of the policy engine's hot-key splits."),
    EnvVar("TORCHSTORE_TPU_TENANT", "str", "",
           "Tenant/cohort label this process's client carries: admission "
           "buckets, loadgen op records, and scoreboard rows are keyed "
           "by it (empty reads as 'default')."),
    # --- elastic fleet autoscaling (torchstore_tpu/autoscale/) --------------
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_INTERVAL_S", "float", 0,
           "Elastic-fleet autoscaler reconcile period, seconds: every "
           "interval the controller snapshots fleet telemetry, runs the "
           "pure autoscale solver, and applies/audits scale decisions "
           "(drain, retire, blob demotion; scale-out spawns defer to "
           "ts.autoscale() client-side). <= 0 (the default) disables the "
           "periodic loop; ts.autoscale() / ts.autoscale_plan() still "
           "serve on demand."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_MIN_VOLUMES", "int", 1,
           "Autoscale solver: never drain the fleet below this many live "
           "volumes (scale-in floor)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_MAX_VOLUMES", "int", 8,
           "Autoscale solver: never scale the fleet above this many live "
           "volumes (scale-out ceiling)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_OUT_INFLIGHT", "int", 8,
           "Autoscale solver: any volume holding at least this many open "
           "landing brackets in the snapshot counts as saturated and "
           "votes for scale-out (a sustained landing-inflight trend from "
           "the history detectors votes the same way)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_OUT_WINDOW_BYTES", "int", 33554432,
           "Autoscale solver: mean rolling-window bytes per live volume "
           "at or above this threshold votes for scale-out (sustained "
           "fleet-wide pressure, not one hot volume — that is the "
           "placement engine's job)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_IDLE_WINDOW_BYTES", "int", 65536,
           "Autoscale solver: the fleet counts as idle only when EVERY "
           "live volume's rolling window moved fewer than this many "
           "bytes (and no landing brackets are open, and no sustained "
           "overload trend is active)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_IDLE_ROUNDS", "int", 3,
           "Autoscale hysteresis: scale-in (drain entry) requires this "
           "many CONSECUTIVE idle reconcile rounds first — one quiet "
           "snapshot between bursts must not start retiring capacity."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_DRAIN_KEYS_PER_ROUND", "int", 64,
           "Autoscale: resident keys migrated off a draining volume per "
           "reconcile round (graceful drain is incremental; the volume "
           "retires only when its index entry count reaches zero)."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_BLOB_KEYS_PER_ROUND", "int", 32,
           "Autoscale: spilled (disk-tier) keys demoted to the blob cold "
           "tier per volume per reconcile round when the blob tier is "
           "enabled."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_COOLDOWN_S", "float", 60.0,
           "Autoscale hysteresis: a subject acted on (or attempted) "
           "within this window is not acted on again, and a reversal "
           "(scale-out after scale-in, or vice versa) is damped for "
           "twice the window — the fleet must converge, not flap."),
    EnvVar("TORCHSTORE_TPU_AUTOSCALE_MAX_ACTIONS", "int", 4,
           "Autoscale solver: cap on actions per reconcile round "
           "(retire/drain continuations first); convergence happens over "
           "rounds, not in one stop-the-world batch."),
    # --- blob cold tier (torchstore_tpu/tiering/blob.py) --------------------
    EnvVar("TORCHSTORE_TPU_BLOB_ENABLED", "bool", False,
           "Enable the object-storage-style blob cold tier: volumes "
           "archive cold spilled entries below the disk tier, fault them "
           "back in through the get-RPC bracket, and the fleet gains "
           "scale-to-zero (ts.blob_checkpoint() + ts.blob_restore())."),
    EnvVar("TORCHSTORE_TPU_BLOB_DIR", "path", None,
           "Blob store root directory (shared by every volume — it "
           "emulates one bucket). Default: <tmpdir>/torchstore_tpu_blob. "
           "Objects persist across fleet restarts; point tests at a "
           "per-run directory for isolation."),
    EnvVar("TORCHSTORE_TPU_BLOB_LATENCY_MS", "float", 0,
           "Injected per-operation latency, milliseconds, on every blob "
           "store op (put/get/list/delete) — emulates object-storage "
           "round-trip time so benches and chaos runs exercise realistic "
           "cold-tier economics."),
    EnvVar("TORCHSTORE_TPU_BLOB_RATE_MBPS", "float", 0,
           "Blob store throughput cap, MiB/s: data-bearing ops stall to "
           "stay under it (an emulated egress/ingress rate limit). <= 0 "
           "(the default) disables the cap."),
    # --- cold-start provisioning (prewarm) ----------------------------------
    EnvVar("TORCHSTORE_TPU_PREWARM_AUTO", "bool", True,
           "put_state_dict derives a manifest and provisions pools/dials "
           "before the first data-plane puts of a large working set."),
    EnvVar("TORCHSTORE_TPU_PREWARM_AUTO_MIN_BYTES", "int", 33554432,
           "Working sets below this many bytes skip the automatic prewarm "
           "hint."),
    EnvVar("TORCHSTORE_TPU_PREWARM_HUGEPAGES", "bool", True,
           "madvise(MADV_HUGEPAGE) on provisioned segments while untouched "
           "(fail-open to plain pages)."),
    EnvVar("TORCHSTORE_TPU_PREWARM_THREADS", "int", 0,
           "Threads for the native prefault of provisioned segments "
           "(0 = auto, one per 16 MiB)."),
    # --- security -----------------------------------------------------------
    EnvVar("TORCHSTORE_TPU_AUTH_SECRET", "str", "",
           "Shared secret for HMAC challenge-response connection auth on "
           "every listener; empty disables auth (loopback-only deployments)."),
    # --- timeouts (seconds) -------------------------------------------------
    EnvVar("TORCHSTORE_TPU_RPC_TIMEOUT", "float", 120,
           "Default control-plane RPC deadline in seconds (<= 0 disables); "
           "data-plane RPCs scale it with payload size."),
    EnvVar("TORCHSTORE_TPU_HANDSHAKE_TIMEOUT", "float", 60,
           "Transport handshake deadline, seconds."),
    EnvVar("TORCHSTORE_TPU_DIRECT_SETTLE_TIMEOUT", "float", 30,
           "How long a direct weight-sync pull waits for the source seqlock "
           "generation to settle (even), seconds."),
    # --- logging / observability --------------------------------------------
    EnvVar("TORCHSTORE_TPU_LOG_LEVEL", "str", "WARNING",
           "Root level for torchstore loggers."),
    EnvVar("TORCHSTORE_TPU_TRACE", "path", None,
           "Write Chrome-trace span events to this file (pid-suffixed per "
           "process); merge with ts.collect_trace() / scripts/merge_traces.py."),
    EnvVar("TORCHSTORE_TPU_TRACE_RUN", "str", None,
           "Internal: per-run id the spawner stamps so reused trace OUTDIRs "
           "can arbitrate file ownership. Set automatically; do not set by "
           "hand."),
    EnvVar("TORCHSTORE_TPU_METRICS_DUMP", "path", None,
           "Every process periodically rewrites this file with its metrics "
           "registry (.json, or .prom for Prometheus text)."),
    EnvVar("TORCHSTORE_TPU_METRICS_INTERVAL_S", "float", 60,
           "Metrics dump period, seconds."),
    EnvVar("TORCHSTORE_TPU_METRICS_PORT", "int", None,
           "Serve live /metrics + /metrics.json + /healthz on this port "
           "from every process (ephemeral-port fallback on sibling "
           "conflicts, published via the ts_metrics_http_port gauge)."),
    EnvVar("TORCHSTORE_TPU_METRICS_HOST", "str", "127.0.0.1",
           "Bind address for the metrics HTTP exporter."),
    EnvVar("TORCHSTORE_TPU_SLOW_OP_MS", "float", None,
           "Client ops / volume puts+gets slower than this many "
           "milliseconds log a warning with the trace id and count "
           "ts_slow_ops_total."),
    EnvVar("TORCHSTORE_TPU_LEDGER", "bool", True,
           "Traffic ledger: per-(peer host, volume, transport, direction) "
           "byte/op accounting with per-key rolling windows, recorded at "
           "every transport choke point (incl. the zero-RPC one-sided "
           "paths) and merged fleet-wide by ts.traffic_matrix()."),
    EnvVar("TORCHSTORE_TPU_LEDGER_WINDOW_S", "float", 300,
           "Rolling per-key traffic-window width, seconds (the ledger "
           "keeps the current + previous window; a key that stops moving "
           "decays out within two)."),
    EnvVar("TORCHSTORE_TPU_FLIGHT_RECORDER", "bool", True,
           "Always-on flight recorder: a bounded per-process ring of "
           "recent ops/transfers/faults/errors, auto-dumped as a JSON "
           "post-mortem on quarantine, repair, wedged streams, injected "
           "deaths, and unclean exits; merged on demand via "
           "ts.flight_record()."),
    EnvVar("TORCHSTORE_TPU_FLIGHT_EVENTS", "int", 4096,
           "Flight-recorder ring capacity (events per process)."),
    EnvVar("TORCHSTORE_TPU_FLIGHT_DIR", "path", None,
           "Directory for flight-recorder post-mortem dumps (default: "
           "<tmpdir>/torchstore_tpu_flight; one file per trigger per "
           "pid, atomically replaced)."),
    EnvVar("TORCHSTORE_TPU_FLIGHT_MIN_INTERVAL_S", "float", 30,
           "Per-trigger-kind flight-dump rate limit: under a sustained "
           "fault storm at most one post-mortem per kind per this many "
           "seconds is written (the rest are counted in "
           "ts_flight_dumps_dropped_total). 0 disables the limit."),
    EnvVar("TORCHSTORE_TPU_HISTORY", "bool", True,
           "Time-series history: a background sampler sweeps every "
           "registry instrument into bounded multi-resolution rings "
           "(1s x 300 / 10s x 360 / 60s x 360, min/max/last per bucket; "
           "counters also derive :rate series), queried locally via "
           "observability.history() and fleet-wide via ts.history()."),
    EnvVar("TORCHSTORE_TPU_HISTORY_INTERVAL_S", "float", 1,
           "History sampling period, seconds. The measured sweep cost "
           "may stretch the effective period (see "
           "TORCHSTORE_TPU_HISTORY_BUDGET_PCT)."),
    EnvVar("TORCHSTORE_TPU_HISTORY_MAX_SERIES", "int", 256,
           "Hard cap on distinct series a process's history store will "
           "track; overflow series are counted in "
           "ts_history_series_dropped_total, never allocated."),
    EnvVar("TORCHSTORE_TPU_HISTORY_BUDGET_PCT", "float", 1,
           "CPU budget for the history sampler as a percent of one core: "
           "the effective interval is raised to sweep_cost / budget so "
           "sampling can never exceed this fraction, however many series "
           "the registry grows."),
    EnvVar("TORCHSTORE_TPU_HISTORY_DUMP_SERIES", "str", None,
           "Comma-separated series globs embedded in flight-recorder "
           "post-mortems (default: a curated vitals set — op quantiles, "
           "landing inflight, client op counters, doorbell residency, "
           "metadata queue depth, SLO breach counts)."),
    EnvVar("TORCHSTORE_TPU_TREND_SUSTAIN_SAMPLES", "int", 5,
           "Consecutive history samples at/over threshold before a "
           "sustained-kind trend detector fires (burst vs regime-change "
           "discrimination for slo_report()['trends'] and the control "
           "snapshot's sustained_overload signal)."),
    EnvVar("TORCHSTORE_TPU_TREND_INFLIGHT", "int", None,
           "Landing-inflight threshold for the sustained/ramp trend "
           "detectors (default: TORCHSTORE_TPU_CONTROL_OVERLOAD_INFLIGHT "
           "— 'the solver's own overload line, held')."),
    # --- SLOs (TORCHSTORE_TPU_SLO_* is a registered dynamic family:
    # operators may add their own; these are the shipped, wired-up bars.
    # Unset = disabled; breaches log + count ts_slo_violations_total) ----
    EnvVar("TORCHSTORE_TPU_SLO_PUT_P99_MS", "float", None,
           "SLO: rolling-window put p99 above this many milliseconds is "
           "a violation."),
    EnvVar("TORCHSTORE_TPU_SLO_GET_P99_MS", "float", None,
           "SLO: rolling-window get p99 above this many milliseconds is "
           "a violation."),
    EnvVar("TORCHSTORE_TPU_SLO_VERSION_LAG", "float", None,
           "SLO: a subscriber acquiring with more than this many "
           "published-but-never-pulled versions behind is a violation."),
    EnvVar("TORCHSTORE_TPU_SLO_FIRST_LAYER_MS", "float", None,
           "SLO: stream begin to a subscriber's first served layer above "
           "this many milliseconds is a violation."),
    EnvVar("TORCHSTORE_TPU_SLO_OVERLAP_MIN", "float", None,
           "SLO: a streamed acquire overlapping LESS than this fraction "
           "of the publish window is a violation."),
    # --- runtime / fleet ----------------------------------------------------
    EnvVar("TORCHSTORE_TPU_BIND_HOST", "str", "127.0.0.1",
           "Bind address for actor, bulk, and device-transfer listeners "
           "(set 0.0.0.0 for multi-host DCN)."),
    EnvVar("TORCHSTORE_TPU_ADVERTISE_HOST", "str", None,
           "Reachable address advertised in actor refs and bulk endpoints "
           "when binding 0.0.0.0/:: (default: the real hostname)."),
    EnvVar("TORCHSTORE_TPU_MP_CONTEXT", "str", "forkserver",
           "Multiprocessing start method for actor children (forkserver "
           "amortizes interpreter startup; spawn remains available)."),
    EnvVar("TORCHSTORE_TPU_HOSTNAME", "str", None,
           "Override the hostname strategies use for same-host transport "
           "selection (tests / containers with unstable hostnames)."),
    EnvVar("TORCHSTORE_TPU_VOLUME_ID", "str", None,
           "Force a spawned storage volume's id (volume replacement and "
           "repair flows)."),
    EnvVar("TORCHSTORE_TPU_STORAGE_DIR", "path", None,
           "Durable backend directory for storage volumes (unset = "
           "in-memory only)."),
    EnvVar("TORCHSTORE_TPU_RECLAIM_DELAYS", "str", None,
           "Comma-separated backoff delays, seconds, for the controller's "
           "stale-replica reclaim drainer (default 1,5,15,60; malformed "
           "values fall back). Parsed into an explicit-delays RetryPolicy."),
    # --- self-healing: health supervisor + retry/failover -------------------
    EnvVar("TORCHSTORE_TPU_HEALTH_INTERVAL_S", "float", 2.0,
           "Controller heartbeat period, seconds: every interval the health "
           "supervisor pings every volume. <= 0 disables the supervisor "
           "(quarantine and auto-repair never trigger)."),
    EnvVar("TORCHSTORE_TPU_HEALTH_MISS_THRESHOLD", "int", 3,
           "Consecutive missed heartbeats that quarantine a volume; the "
           "same count of consecutive successful pings reinstates a "
           "quarantined volume through probation."),
    EnvVar("TORCHSTORE_TPU_AUTO_REPAIR", "bool", True,
           "Quarantining a volume automatically re-replicates every key it "
           "held that still has a healthy copy onto healthy volumes "
           "(volume-to-volume, no client involvement). Off: quarantine "
           "only, redundancy stays degraded until ts.repair()."),
    EnvVar("TORCHSTORE_TPU_FAULTPOINTS", "str", None,
           "Arm deterministic fault injection at named sites, e.g. "
           "'volume.put=raise:count=2;actor.ping=wedge'. Parsed at process "
           "start (and after fork) in every store process; see "
           "torchstore_tpu/faults.py for the site registry and actions. "
           "Test/chaos tooling only — leave unset in production."),
    EnvVar("TORCHSTORE_TPU_RETRY_BASE_S", "float", 0.05,
           "Unified RetryPolicy: first backoff delay, seconds."),
    EnvVar("TORCHSTORE_TPU_RETRY_MAX_S", "float", 2.0,
           "Unified RetryPolicy: backoff ceiling, seconds."),
    EnvVar("TORCHSTORE_TPU_RETRY_MULTIPLIER", "float", 2.0,
           "Unified RetryPolicy: exponential backoff multiplier."),
    EnvVar("TORCHSTORE_TPU_RETRY_JITTER", "float", 0.1,
           "Unified RetryPolicy: fraction of each delay randomized "
           "(de-synchronizes fleet-wide retry storms)."),
    EnvVar("TORCHSTORE_TPU_RETRY_DEADLINE_S", "float", 30.0,
           "Unified RetryPolicy: total retry budget per logical operation, "
           "seconds; the first failure after the deadline surfaces."),
    # --- bench --------------------------------------------------------------
    EnvVar("TORCHSTORE_TPU_BENCH_COLD_MB", "int", None,
           "bench.py cold-path working-set size in MB (default scales with "
           "the bench tensor set)."),
    EnvVar("TORCHSTORE_TPU_BENCH_DEVICE", "str", "1",
           "Set 0/false to skip bench.py device phases."),
    EnvVar("TORCHSTORE_TPU_BENCH_DEVICE_ALLOW_CPU", "bool", False,
           "Allow bench.py device phases on CPU jax (interpret mode) "
           "instead of refusing."),
)

# Dynamic families: names extending these prefixes are per-instance handles
# (one per store) or operator-extensible knob families (custom SLOs), not
# individually registrable entries.
ENV_PREFIXES: tuple[str, ...] = (
    "TORCHSTORE_TPU_STORE_",
    "TORCHSTORE_TPU_SLO_",
)


def env_registry_entry(name: str) -> EnvVar | None:
    for entry in ENV_REGISTRY:
        if entry.name == name:
            return entry
    return None


def _env_bool(name: str, default: bool) -> bool:
    val = os.environ.get(name)
    if val is None:
        return default
    return val.strip().lower() not in ("0", "false", "no", "off", "")


def _env_int(name: str, default: int) -> int:
    val = os.environ.get(name)
    return int(val) if val is not None else default


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def _env_float(name: str, default: float) -> float:
    val = os.environ.get(name)
    return float(val) if val is not None else default


@dataclass(frozen=True)
class RetryPolicy:
    """The ONE retry/backoff vocabulary for the whole store.

    Every layer that retries — client get failover, non-replicated put
    transport demotion, weight-channel publish/acquire survival, the
    controller's stale-replica reclaim drainer — derives its schedule from
    an instance of this type instead of inventing env-list parsing or
    hardcoded deadlines (enforced by the ``retry-discipline`` tslint rule).

    Delay for attempt ``i`` (0-based) is ``min(max_s, base_s *
    multiplier**i)`` with ``jitter`` fraction of it randomized, unless
    ``delays`` pins an explicit schedule (then the schedule IS the attempt
    budget). ``deadline_s`` bounds the TOTAL time spent retrying one
    logical operation: the first failure after the deadline surfaces.
    Frozen + picklable: it rides StoreConfig through actor RPCs."""

    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline_s: float = 30.0
    # Explicit delay schedule (seconds). When set, backoff() indexes into it
    # and attempts are capped at len(delays); the reclaim drainer's
    # TORCHSTORE_TPU_RECLAIM_DELAYS compatibility rides this.
    delays: Optional[tuple[float, ...]] = None

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            base_s=_env_float("TORCHSTORE_TPU_RETRY_BASE_S", 0.05),
            max_s=_env_float("TORCHSTORE_TPU_RETRY_MAX_S", 2.0),
            multiplier=_env_float("TORCHSTORE_TPU_RETRY_MULTIPLIER", 2.0),
            jitter=_env_float("TORCHSTORE_TPU_RETRY_JITTER", 0.1),
            deadline_s=_env_float("TORCHSTORE_TPU_RETRY_DEADLINE_S", 30.0),
        )

    @classmethod
    def from_delays(
        cls, delays, deadline_s: Optional[float] = None
    ) -> "RetryPolicy":
        delays = tuple(float(d) for d in delays)
        if not delays:
            raise ValueError("explicit delay schedule must not be empty")
        return cls(
            deadline_s=sum(delays) * 2 if deadline_s is None else deadline_s,
            delays=delays,
        )

    @property
    def max_attempts(self) -> Optional[int]:
        """Bound on RETRIES (not first attempts): None = deadline-limited."""
        return len(self.delays) if self.delays is not None else None

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (0-based), jittered."""
        if self.delays is not None:
            delay = self.delays[min(attempt, len(self.delays) - 1)]
        else:
            delay = min(self.max_s, self.base_s * self.multiplier**attempt)
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(0.0, delay)

    def start(self) -> float:
        """Monotonic deadline for one logical operation's retry budget."""
        return time.monotonic() + self.deadline_s

    def should_retry(self, attempt: int, deadline: float) -> bool:
        """Whether retry ``attempt`` (0-based) may still run: within both
        the attempt cap (explicit schedules) and the time budget."""
        if self.delays is not None and attempt >= len(self.delays):
            return False
        return time.monotonic() < deadline


def _default_shm_pool_cap() -> int:
    """Quarter of /dev/shm's AVAILABLE space at startup, clamped to
    [4 GB, 64 GB]. Available (not total) leaves room for live + retired
    segments and other tenants; the 64 GB ceiling bounds how many written
    tmpfs pages recycled segments may pin on huge hosts. Model-scale syncs
    (16 GB for Llama-3-8B bf16) need the pool to hold roughly one working
    set or puts fall back to cold tmpfs allocation."""
    try:
        stat = os.statvfs("/dev/shm")
        avail = stat.f_frsize * stat.f_bavail
    except OSError:
        return 4 << 30
    return max(4 << 30, min(avail // 4, 64 << 30))


@dataclass
class StoreConfig:
    """All tunables for one store instance. Field defaults come from env vars
    (prefix ``TORCHSTORE_TPU_``) so operator overrides keep working, but the
    object is the source of truth once a store is initialized."""

    # --- transports ---------------------------------------------------------
    shm_enabled: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_SHM_ENABLED", True)
    )
    bulk_tcp_enabled: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_BULK_TCP_ENABLED", True)
    )
    ici_enabled: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_ICI_ENABLED", True)
    )
    # Zero-copy SHM gets: same-host fetches without an in-place destination
    # return read-only snapshot views of the volume's segments instead of
    # copies. Safe by default: the volume lease-counts served views and
    # retires (never overwrites) a viewed segment on the next put, so a held
    # view is always an immutable snapshot.
    zero_copy_get: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_ZERO_COPY_GET", True)
    )
    # Cap on the volume-side pool of recycled SHM segments (bytes). Released
    # segments beyond the cap are unlinked oldest-first. Default: a quarter
    # of /dev/shm's AVAILABLE space at startup, clamped to [4 GB, 64 GB]
    # (see _default_shm_pool_cap). Size it to hold at least one working set
    # — a model-scale sync (16 GB for Llama-3-8B bf16) collapses to cold
    # tmpfs allocation if the pool can't retain it.
    shm_pool_max_bytes: int = field(
        default_factory=lambda: _env_int(
            "TORCHSTORE_TPU_SHM_POOL_MAX_BYTES", _default_shm_pool_cap()
        )
    )
    # Use the native C++ data-path library when built.
    use_native: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_USE_NATIVE", True)
    )

    # --- steady-state sync pipeline -----------------------------------------
    # Landing-copy pool: client/volume-side segment copies fan out to this
    # many threads so they overlap each other and the event loop's RPC work
    # (0 = auto, one per core capped at 4; fast_copy already threads
    # internally for large arrays, so the pool budgets against cores).
    landing_threads: int = field(
        default_factory=lambda: _env_int("TORCHSTORE_TPU_LANDING_THREADS", 0)
    )
    # Small-key arena packing threshold: tensors at or below this many bytes
    # share one arena segment per put batch (0 disables).
    arena_max_bytes: int = field(
        default_factory=lambda: _env_int(
            "TORCHSTORE_TPU_ARENA_MAX_BYTES", 256 << 10
        )
    )
    # Default wire quantization for state-dict publishes (none|int8|
    # int8_block|int4_block) and the blockwise scale granularity. See
    # state_dict_utils' quant tier: fused blobs, scales in the payload's
    # arena segment, plan-cacheable.
    transfer_quant: str = field(
        default_factory=lambda: _env_str("TORCHSTORE_TPU_TRANSFER_QUANT", "none")
    )
    quant_block: int = field(
        default_factory=lambda: _env_int(
            "TORCHSTORE_TPU_TRANSFER_QUANT_BLOCK", 256
        )
    )
    # Delta wire tier cadence/threshold (weight_channel delta publishes).
    delta_keyframe: int = field(
        default_factory=lambda: _env_int("TORCHSTORE_TPU_DELTA_KEYFRAME", 8)
    )
    delta_skip_eps: float = field(
        default_factory=lambda: _env_float("TORCHSTORE_TPU_DELTA_SKIP_EPS", 0.0)
    )
    # Iteration-stable transfer-plan cache for put/get_state_dict.
    plan_cache: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_PLAN_CACHE", True)
    )
    # One-sided data plane: warm same-host gets are seqlock-stamped direct
    # segment reads (zero RPCs); warm cross-host gets ring a bulk doorbell
    # against a volume-cached plan. Stale/torn reads fail over loudly to
    # the RPC path and bump ts_one_sided_fallbacks_total.
    one_sided: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_ONE_SIDED", True)
    )
    # Layer-streamed sync: long-poll window per wait_for_stream round and
    # the mixed-generation/superseded re-acquire budget (stream_sync.py).
    stream_poll_s: float = field(
        default_factory=lambda: _env_float("TORCHSTORE_TPU_STREAM_POLL_S", 10.0)
    )
    stream_retries: int = field(
        default_factory=lambda: _env_int("TORCHSTORE_TPU_STREAM_RETRIES", 2)
    )
    # Broadcast distribution: whether this client may join relay trees
    # (per-channel opt-in still required — WeightSubscriber(relay=True)).
    # Fanout and the re-parent window are CONTROLLER-side knobs read from
    # env in the controller process; they live in the registry above.
    relay_enabled: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_RELAY_ENABLED", True)
    )
    # Scale-out metadata plane: controller shard count (1 = classic single
    # controller; initialize(controller_shards=) overrides) and whether
    # this client attaches same-host stamped metadata segments for
    # zero-RPC warm locates / plan validation / stream polling.
    controller_shards: int = field(
        default_factory=lambda: max(
            1, _env_int("TORCHSTORE_TPU_CONTROLLER_SHARDS", 1)
        )
    )
    meta_stamped: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_META_STAMPED", True)
    )

    # --- control plane (client-side half) -----------------------------------
    # Per-tenant admission control: when on, put/get batches reserve tokens
    # from a tenant-labeled bucket whose refill scales down under fleet
    # overload signals (see torchstore_tpu/control/admission.py). The
    # solver/engine knobs are CONTROLLER-side env reads (control/engine.py).
    control_admission: bool = field(
        default_factory=lambda: _env_bool(
            "TORCHSTORE_TPU_CONTROL_ADMISSION", False
        )
    )
    admit_rate_hz: float = field(
        default_factory=lambda: _env_float(
            "TORCHSTORE_TPU_CONTROL_ADMIT_RATE", 512.0
        )
    )
    # None: the bucket defaults to 2x the rate (AdmissionController).
    admit_burst: Optional[float] = field(
        default_factory=lambda: (
            float(v)
            if (v := os.environ.get("TORCHSTORE_TPU_CONTROL_ADMIT_BURST"))
            else None
        )
    )
    overload_inflight: int = field(
        default_factory=lambda: _env_int(
            "TORCHSTORE_TPU_CONTROL_OVERLOAD_INFLIGHT", 16
        )
    )
    # Hot-key read spreading: rotate first-replica choice by a stable
    # per-client salt so split replicas actually share the read load.
    replica_spread: bool = field(
        default_factory=lambda: _env_bool(
            "TORCHSTORE_TPU_CONTROL_REPLICA_SPREAD", False
        )
    )
    # Tenant/cohort label for admission buckets and loadgen attribution.
    tenant: str = field(
        default_factory=lambda: _env_str("TORCHSTORE_TPU_TENANT", "")
    )

    # --- cold-start provisioning (prewarm) ----------------------------------
    # Automatic hint path: put_state_dict derives a manifest from the state
    # dict and provisions pools/dials BEFORE the data-plane puts, so the very
    # first sync of a working set draws pre-faulted segments instead of
    # allocating cold on the critical path. Only fires for working sets of
    # prewarm_auto_min_bytes or more (tiny dicts would pay RPC overhead for
    # nothing) and at most once per distinct size-signature per client.
    prewarm_auto: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_PREWARM_AUTO", True)
    )
    prewarm_auto_min_bytes: int = field(
        default_factory=lambda: _env_int(
            "TORCHSTORE_TPU_PREWARM_AUTO_MIN_BYTES", 32 << 20
        )
    )
    # madvise(MADV_HUGEPAGE) on provisioned segments so tmpfs backs them with
    # transparent huge pages where the kernel allows (fewer TLB misses on the
    # hot memcpy; fail-open — plain pages otherwise).
    prewarm_hugepages: bool = field(
        default_factory=lambda: _env_bool("TORCHSTORE_TPU_PREWARM_HUGEPAGES", True)
    )
    # Threads for the native prefault of provisioned segments (0 = auto).
    prewarm_threads: int = field(
        default_factory=lambda: _env_int("TORCHSTORE_TPU_PREWARM_THREADS", 0)
    )

    # --- security -----------------------------------------------------------
    # Shared secret for connection auth (HMAC challenge-response on every
    # actor/rendezvous/bulk/peer-read listener). Empty = auth disabled; set
    # it (same value on every host) for any non-loopback deployment — these
    # protocols unpickle peer payloads and must not accept strangers.
    auth_secret: str = field(
        default_factory=lambda: _env_str("TORCHSTORE_TPU_AUTH_SECRET", "")
    )

    # --- timeouts (seconds) -------------------------------------------------
    rpc_timeout: float = field(
        default_factory=lambda: float(_env_str("TORCHSTORE_TPU_RPC_TIMEOUT", "120"))
    )
    handshake_timeout: float = field(
        default_factory=lambda: float(
            _env_str("TORCHSTORE_TPU_HANDSHAKE_TIMEOUT", "60")
        )
    )
    # How long a direct pull waits for a source's seqlock generation to
    # settle (even) before giving up. Model-scale refreshes / fallback
    # stagings legitimately hold the generation odd for seconds.
    direct_settle_timeout: float = field(
        default_factory=lambda: float(
            _env_str("TORCHSTORE_TPU_DIRECT_SETTLE_TIMEOUT", "30")
        )
    )

    # --- retry / failover ---------------------------------------------------
    # The unified retry policy every layer derives backoff schedules from
    # (client failover, put transport demotion, publish/acquire survival).
    retry: RetryPolicy = field(default_factory=RetryPolicy.from_env)

    # --- logging ------------------------------------------------------------
    log_level: str = field(
        default_factory=lambda: _env_str("TORCHSTORE_TPU_LOG_LEVEL", "WARNING")
    )

    def merged(self, **overrides) -> "StoreConfig":
        return replace(self, **overrides)


_default_config: StoreConfig | None = None


def default_config() -> StoreConfig:
    global _default_config
    if _default_config is None:
        _default_config = StoreConfig()
    return _default_config
