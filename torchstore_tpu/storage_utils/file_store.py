"""Durable storage backend: a file-backed ``StorageImpl``.

The reference is strictly in-memory (/root/reference/torchstore/
storage_volume.py:146-151) — volume death loses everything. This backend
persists entries under a directory and serves tensors as writable
``np.memmap`` views, so:

- gets read through the page cache (no explicit load step);
- in-place overwrites (invariant 6) write straight through to disk;
- a restarted volume pointed at the same directory recovers every entry,
  and the controller rebuilds its index from volume manifests
  (``Controller.rebuild_index``) — crash recovery the reference lacks.

Layout: ``<root>/<urlsafe(key)>/meta.pkl`` + ``data.bin`` (tensor) or
``shard_<i>.bin`` (sharded, coords in meta) or inline object in meta.

Crash safety (the spill-tier contract, torchstore_tpu/tiering/spill.py):
every FRESH persist is write-temp → flush+fsync → rename, and meta.pkl is
fsynced before its atomic replace — a process killed at ANY instant leaves
either no entry (meta absent / still the old one) or a complete one, never
a torn data file a later fault-in would trust. Leftover ``*.tmp`` files
from a mid-write death are swept at load. In-place overwrites through a
served memmap (invariant 6) deliberately keep writing the committed file —
aliasing readers must observe them — so their durability is page-cache
best-effort, exactly as before.
"""

from __future__ import annotations

import base64
import os
import pickle
import shutil
from typing import Any

import numpy as np

from torchstore_tpu.storage_volume import (
    KeyNotFoundError,
    StorageImpl,
)
from torchstore_tpu.transport.types import Request, TensorMeta, TensorSlice

_META = "meta.pkl"


def _keydir(root: str, key: str) -> str:
    return os.path.join(
        root, base64.urlsafe_b64encode(key.encode()).decode().rstrip("=")
    )


def _dir_key(name: str) -> str:
    pad = "=" * (-len(name) % 4)
    return base64.urlsafe_b64decode(name + pad).decode()


def _shard_file(coords: tuple) -> str:
    return "shard_" + "_".join(str(c) for c in coords) + ".bin"


def _map_file(path: str, dtype, shape, mode: str) -> np.ndarray:
    """np.memmap that tolerates zero-size arrays (mmap refuses empty files;
    empty tensors live as meta + plain array)."""
    import math as _math

    if _math.prod(shape) == 0:
        return np.empty(shape, dtype=dtype)
    return np.memmap(path, dtype=dtype, mode=mode, shape=tuple(shape))


def _same_memory(a: np.ndarray, b: np.ndarray) -> bool:
    """True when both arrays cover the same buffer (np.asarray of a memmap
    returns a plain-ndarray VIEW, so object identity is not enough — and
    re-persisting would truncate the very file the source view maps)."""
    return (
        a.__array_interface__["data"][0] == b.__array_interface__["data"][0]
        and a.nbytes == b.nbytes
    )


class FileBackedStore(StorageImpl):
    """Same contract as InMemoryStore, with a directory as truth. Arrays in
    ``self.kv`` are np.memmap views over the entry files."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # key -> entry dicts shaped exactly like InMemoryStore's.
        self.kv: dict[str, dict] = {}
        self._load_all()

    # ---- persistence -----------------------------------------------------

    def _load_all(self) -> None:
        for name in os.listdir(self.root):
            path = os.path.join(self.root, name)
            if os.path.isdir(path):
                # Sweep torn temp files a mid-write death left behind: the
                # rename never committed them, so they are garbage bytes no
                # entry references — and an entry dir holding ONLY a .tmp
                # (no meta.pkl) is an aborted first persist, skipped below.
                for fname in os.listdir(path):
                    if fname.endswith(".tmp"):
                        try:
                            os.unlink(os.path.join(path, fname))
                        except OSError:
                            pass
            meta_path = os.path.join(path, _META)
            if not os.path.isfile(meta_path):
                continue
            try:
                with open(meta_path, "rb") as f:
                    meta = pickle.load(f)
                key = _dir_key(name)
                self.kv[key] = self._open_entry(path, meta)
            except Exception:  # pragma: no cover - corrupt entry
                from torchstore_tpu.logging import get_logger

                get_logger("torchstore_tpu.file_store").warning(
                    "skipping corrupt entry %s", path
                )

    def _open_entry(self, path: str, meta: dict) -> dict:
        if meta["type"] == "object":
            return {"type": "object", "obj": meta["obj"]}
        if meta["type"] == "tensor":
            tm: TensorMeta = meta["meta"]
            arr = _map_file(
                os.path.join(path, "data.bin"), tm.np_dtype, tm.shape, "r+"
            )
            return {"type": "tensor", "tensor": arr}
        shards = {}
        for coords, ts in meta["slices"].items():
            arr = _map_file(
                os.path.join(path, _shard_file(coords)),
                TensorMeta(shape=(), dtype=meta["dtype"]).np_dtype,
                ts.local_shape,
                "r+",
            )
            shards[coords] = {"slice": ts, "tensor": arr}
        return {"type": "sharded", "shards": shards}

    def _write_meta(self, path: str, meta: dict) -> None:
        tmp = os.path.join(path, _META + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(path, _META))  # atomic commit

    def _persist_file(self, path: str, fname: str, arr: np.ndarray) -> np.ndarray:
        """Crash-safe fresh persist of one array: write a temp sibling,
        flush + fsync it, atomically rename into place, then serve a memmap
        of the COMMITTED file. A death at any point leaves at worst a .tmp
        the loader sweeps — never a torn ``fname`` (the spill tier's
        fault-in path trusts every committed data file unconditionally)."""
        from torchstore_tpu.native import fast_copy

        if arr.size == 0:
            return np.empty(arr.shape, dtype=arr.dtype)
        final = os.path.join(path, fname)
        tmp = final + ".tmp"
        mm = _map_file(tmp, arr.dtype, arr.shape, "w+")
        fast_copy(mm, np.ascontiguousarray(arr))
        mm.flush()  # msync the mapping before fsyncing the inode
        with open(tmp, "rb+") as f:
            os.fsync(f.fileno())
        del mm  # release the temp mapping before the rename commits
        os.replace(tmp, final)
        return _map_file(final, arr.dtype, arr.shape, "r+")

    def _persist_tensor(self, key: str, arr: np.ndarray) -> np.ndarray:
        path = _keydir(self.root, key)
        os.makedirs(path, exist_ok=True)
        mm = self._persist_file(path, "data.bin", arr)
        self._write_meta(
            path, {"type": "tensor", "meta": TensorMeta.of(arr)}
        )
        return mm

    def _persist_shard(
        self, key: str, ts: TensorSlice, arr: np.ndarray, slices: dict
    ) -> np.ndarray:
        path = _keydir(self.root, key)
        os.makedirs(path, exist_ok=True)
        mm = self._persist_file(path, _shard_file(ts.coordinates), arr)
        self._write_meta(
            path,
            {"type": "sharded", "slices": slices, "dtype": str(arr.dtype)},
        )
        return mm

    # ---- StorageImpl contract -------------------------------------------

    def extract_existing(self, metas: list[Request]) -> dict[int, np.ndarray]:
        from torchstore_tpu.storage_volume import InMemoryStore

        return InMemoryStore.extract_existing(self, metas)  # same kv shape

    def _check_type(self, key: str, entry: dict, incoming: str) -> None:
        from torchstore_tpu.storage_volume import InMemoryStore

        InMemoryStore._check_type(self, key, entry, incoming)

    def store(self, metas: list[Request], values: dict[int, Any]) -> None:
        for idx, meta in enumerate(metas):
            if idx not in values:
                raise ValueError(f"transport produced no value for {meta.key!r}")
            value = values[idx]
            entry = self.kv.get(meta.key)
            if meta.is_object:
                if entry is not None:
                    self._check_type(meta.key, entry, "object")
                path = _keydir(self.root, meta.key)
                os.makedirs(path, exist_ok=True)
                self._write_meta(path, {"type": "object", "obj": value})
                self.kv[meta.key] = {"type": "object", "obj": value}
            elif meta.tensor_slice is not None:
                ts = meta.tensor_slice
                if entry is None:
                    entry = {"type": "sharded", "shards": {}}
                    self.kv[meta.key] = entry
                self._check_type(meta.key, entry, "sharded")
                value_np = np.asarray(value)
                # Layout-changing re-publish: delete superseded shard FILES
                # (not just kv entries), or a crash+recover would manifest a
                # mix of old- and new-layout slices for this key.
                from torchstore_tpu.storage_volume import (
                    _prune_superseded_shards,
                )

                stale = _prune_superseded_shards(entry["shards"], ts)
                # meta.pkl records ONE dtype for all of a key's shard files:
                # a dtype-changing re-publish must drop old-dtype files too,
                # or recovery maps them with the new dtype (garbage reads).
                for coords, shard in list(entry["shards"].items()):
                    if shard["tensor"].dtype != value_np.dtype:
                        del entry["shards"][coords]
                        stale.append(coords)
                for coords in stale:
                    try:
                        os.unlink(
                            os.path.join(
                                _keydir(self.root, meta.key), _shard_file(coords)
                            )
                        )
                    except OSError:
                        pass
                existing = entry["shards"].get(ts.coordinates)
                if existing is not None and _same_memory(
                    existing["tensor"], value_np
                ):
                    # Transport wrote into the memmap: data already on disk.
                    # The slice metadata may still have changed (same coords
                    # + local shape but different offsets) — keep meta.pkl
                    # authoritative or recovery restores a stale placement.
                    if existing["slice"] != ts:
                        slices = {
                            c: s["slice"] for c, s in entry["shards"].items()
                        }
                        slices[ts.coordinates] = ts
                        self._write_meta(
                            _keydir(self.root, meta.key),
                            {
                                "type": "sharded",
                                "slices": slices,
                                "dtype": str(value_np.dtype),
                            },
                        )
                    entry["shards"][ts.coordinates]["slice"] = ts
                else:
                    slices = {
                        c: s["slice"] for c, s in entry["shards"].items()
                    }
                    slices[ts.coordinates] = ts
                    mm = self._persist_shard(meta.key, ts, value_np, slices)
                    entry["shards"][ts.coordinates] = {"slice": ts, "tensor": mm}
            else:
                if entry is not None:
                    self._check_type(meta.key, entry, "tensor")
                value_np = np.asarray(value)
                if entry is not None and _same_memory(entry["tensor"], value_np):
                    pass  # in-place overwrite already wrote through the memmap
                else:
                    mm = self._persist_tensor(meta.key, value_np)
                    self.kv[meta.key] = {"type": "tensor", "tensor": mm}

    def get_data(self, meta: Request) -> Any:
        from torchstore_tpu.storage_volume import InMemoryStore

        return InMemoryStore.get_data(self, meta)

    def get_meta(self, meta: Request) -> Any:
        from torchstore_tpu.storage_volume import InMemoryStore

        return InMemoryStore.get_meta(self, meta)

    def _entry(self, key: str) -> dict:
        entry = self.kv.get(key)
        if entry is None:
            raise KeyNotFoundError(f"Key {key!r} not found in storage volume")
        return entry

    def delete(self, key: str) -> bool:
        existed = self.kv.pop(key, None) is not None
        shutil.rmtree(_keydir(self.root, key), ignore_errors=True)
        return existed

    def reset(self) -> None:
        self.kv.clear()
        shutil.rmtree(self.root, ignore_errors=True)
        os.makedirs(self.root, exist_ok=True)

    # ---- recovery --------------------------------------------------------

    def manifest(self) -> list[dict]:
        """``{"meta": Request, "mtime": float}`` for every persisted entry,
        for controller index rebuilds after a restart. File mtimes let the
        rebuild resolve mixed-layout states (a crash mid re-shard leaves one
        volume on the new layout while another still holds old shards) by
        keeping only the newest layout per key."""
        out: list[dict] = []

        def _mtime(*names: str) -> float:
            try:
                return max(
                    os.path.getmtime(os.path.join(path, n)) for n in names
                )
            except OSError:
                return 0.0

        for key, entry in self.kv.items():
            path = _keydir(self.root, key)
            if entry["type"] == "object":
                out.append(
                    {"meta": Request(key=key, is_object=True), "mtime": _mtime(_META)}
                )
            elif entry["type"] == "tensor":
                out.append(
                    {
                        "meta": Request(
                            key=key, tensor_meta=TensorMeta.of(entry["tensor"])
                        ),
                        "mtime": _mtime("data.bin"),
                    }
                )
            else:
                for coords, shard in entry["shards"].items():
                    out.append(
                        {
                            "meta": Request(
                                key=key,
                                tensor_slice=shard["slice"],
                                tensor_meta=TensorMeta.of(shard["tensor"]),
                            ),
                            "mtime": _mtime(_shard_file(coords)),
                        }
                    )
        return out
