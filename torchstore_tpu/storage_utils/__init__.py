from torchstore_tpu.storage_utils.trie import Trie, TrieKeysView

__all__ = ["Trie", "TrieKeysView"]
