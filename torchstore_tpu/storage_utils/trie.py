"""Prefix trie over '/'-separated keys.

Replaces the reference's pygtrie-backed ``Trie``
(/root/reference/torchstore/storage_utils/trie.py:20-177) with a dependency-
free segment trie: a ``MutableMapping`` whose ``keys()`` view supports
``filter_by_prefix`` for ``store.keys(prefix)`` listings.
"""

from __future__ import annotations

from collections.abc import MutableMapping
from typing import Any, Iterator, Optional

_SEP = "/"


class _Node:
    __slots__ = ("children", "value", "has_value")

    def __init__(self) -> None:
        self.children: dict[str, _Node] = {}
        self.value: Any = None
        self.has_value = False


class TrieKeysView:
    """Iterable keys view with prefix filtering (path-segment semantics)."""

    def __init__(self, trie: "Trie", prefix: Optional[str] = None) -> None:
        self._trie = trie
        self._prefix = prefix

    def filter_by_prefix(self, prefix: str) -> "TrieKeysView":
        return TrieKeysView(self._trie, prefix)

    def __iter__(self) -> Iterator[str]:
        yield from self._trie.iter_keys(self._prefix)

    def __len__(self) -> int:
        return sum(1 for _ in self)

    def __contains__(self, key: object) -> bool:
        if not isinstance(key, str) or key not in self._trie:
            return False
        if self._prefix is None:
            return True
        pre = self._prefix.split(_SEP)
        segs = key.split(_SEP)
        return segs[: len(pre)] == pre


class Trie(MutableMapping):
    def __init__(self) -> None:
        self._root = _Node()
        self._len = 0

    @staticmethod
    def _split(key: str) -> list[str]:
        if not isinstance(key, str):
            raise TypeError(f"trie keys must be str, got {type(key)}")
        return key.split(_SEP)

    def _find(self, key: str) -> Optional[_Node]:
        node = self._root
        for seg in self._split(key):
            node = node.children.get(seg)
            if node is None:
                return None
        return node

    def __getitem__(self, key: str) -> Any:
        node = self._find(key)
        if node is None or not node.has_value:
            raise KeyError(key)
        return node.value

    def __setitem__(self, key: str, value: Any) -> None:
        node = self._root
        for seg in self._split(key):
            node = node.children.setdefault(seg, _Node())
        if not node.has_value:
            self._len += 1
        node.value = value
        node.has_value = True

    def __delitem__(self, key: str) -> None:
        segs = self._split(key)
        path: list[tuple[_Node, str]] = []
        node = self._root
        for seg in segs:
            nxt = node.children.get(seg)
            if nxt is None:
                raise KeyError(key)
            path.append((node, seg))
            node = nxt
        if not node.has_value:
            raise KeyError(key)
        node.has_value = False
        node.value = None
        self._len -= 1
        # Prune now-empty branches.
        for parent, seg in reversed(path):
            child = parent.children[seg]
            if child.has_value or child.children:
                break
            del parent.children[seg]

    def __contains__(self, key: object) -> bool:
        if not isinstance(key, str):
            return False
        node = self._find(key)
        return node is not None and node.has_value

    def __len__(self) -> int:
        return self._len

    def __iter__(self) -> Iterator[str]:
        yield from self.iter_keys(None)

    def iter_keys(self, prefix: Optional[str]) -> Iterator[str]:
        """All keys, or keys under ``prefix``. A prefix matches a key when the
        key equals it or extends it at a segment boundary — matching the
        path-wise semantics of the reference's StringTrie
        (/root/reference/torchstore/storage_utils/trie.py:99-106)."""
        node = self._root
        parts: list[str] = []
        if prefix:
            parts = self._split(prefix)
            for seg in parts:
                node = node.children.get(seg)
                if node is None:
                    return
        stack = [(node, parts)]
        while stack:
            cur, path = stack.pop()
            if cur.has_value:
                yield _SEP.join(path)
            for seg in sorted(cur.children, reverse=True):
                stack.append((cur.children[seg], path + [seg]))

    def keys(self) -> TrieKeysView:  # type: ignore[override]
        return TrieKeysView(self)
