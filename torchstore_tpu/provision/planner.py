"""Capacity planner: manifest + fleet topology -> per-volume provisioning.

Pure math, no IO: given a StateDictManifest, the store's volume ids, the
placement strategy (which volumes a put from this client fans out to —
replication included), and each volume's transport rung, produce the
ProvisionPlan the executors drive:

- per volume: the exact {segment size: count} pool the SHM put handshake
  will ask for, the bytes that implies, and how many bulk connections to
  pre-dial (1 main + stripe extras when any single payload exceeds the
  striping threshold);
- clamping: a capacity grant smaller than the ask shrinks the plan
  largest-segments-first (big segments are the expensive cold allocations;
  a clamp should spend its budget where the first sync hurts most).

Everything here is unit-testable without a store (tests/test_provision.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from torchstore_tpu.provision.manifest import StateDictManifest


@dataclass
class VolumePlan:
    """What one volume should be provisioned with before the first sync."""

    volume_id: str
    transport: str  # "shm" | "bulk" | "rpc"
    # {segment size: count} to pre-create into the volume's warm free pool
    # (SHM rung only; other transports carry no segment plan).
    segment_sizes: dict[int, int] = field(default_factory=dict)
    # Bulk connections to pre-dial: 0 for non-bulk rungs, else 1 main
    # (+ stripe extras for payloads above the striping threshold).
    dials: int = 0
    # Bytes the segment plan was shrunk by to fit a capacity grant.
    clamped_bytes: int = 0

    @property
    def planned_bytes(self) -> int:
        return sum(size * count for size, count in self.segment_sizes.items())


@dataclass
class ProvisionPlan:
    volumes: dict[str, VolumePlan] = field(default_factory=dict)
    # Manifest total (pre-replication); per-volume asks can sum to a
    # multiple of this under replicated strategies.
    manifest_bytes: int = 0
    replicas: int = 1
    device_server: bool = False  # prewarm the ICI transfer server too

    @property
    def planned_bytes(self) -> int:
        return sum(p.planned_bytes for p in self.volumes.values())

    @property
    def clamped_bytes(self) -> int:
        return sum(p.clamped_bytes for p in self.volumes.values())


def expected_bulk_conns(manifest: StateDictManifest) -> int:
    """Connections one bulk volume needs for this working set: the main
    promoted connection, plus the stripe set when any single payload will be
    striped."""
    from torchstore_tpu.transport.bulk import STRIPE_CONNS, STRIPE_THRESHOLD

    if manifest.max_request_nbytes() > STRIPE_THRESHOLD:
        return STRIPE_CONNS
    return 1


def plan_provisioning(
    manifest: StateDictManifest,
    put_volume_ids: list[str],
    transports: dict[str, str],
    ici_available: bool = False,
    arena_max_bytes: int = 0,
) -> ProvisionPlan:
    """Build the plan: every volume a put will land on (primary + replicas,
    already resolved by the caller through the strategy) gets the manifest's
    full segment plan on the SHM rung, a dial plan on the bulk rung, and
    nothing on the RPC rung (payloads ride the codec — nothing to warm).
    ``arena_max_bytes`` mirrors the transport's small-key arena packing so
    the provisioned pool matches what the first put's handshake asks for."""
    sizes = manifest.segment_sizes(arena_max_bytes)
    plan = ProvisionPlan(
        manifest_bytes=manifest.total_bytes,
        replicas=max(1, len(put_volume_ids)),
        device_server=bool(ici_available and manifest.device_resident),
    )
    for vid in put_volume_ids:
        transport = transports.get(vid, "rpc")
        vp = VolumePlan(volume_id=vid, transport=transport)
        if transport == "shm":
            vp.segment_sizes = dict(sizes)
        elif transport == "bulk":
            vp.dials = expected_bulk_conns(manifest)
        plan.volumes[vid] = vp
    return plan


def clamp_to_grant(vp: VolumePlan, granted_bytes: Optional[int]) -> VolumePlan:
    """Shrink a volume's segment plan to a capacity grant. ``None`` means
    ungoverned (no clamp); 0 drops the whole plan. The budget is spent
    LARGEST segments first: cold-creating a 256 MB segment on the first
    put's critical path costs orders of magnitude more than a 4 KB one, so
    when tmpfs can't hold everything the big allocations are what prewarm
    must cover. Returns ``vp`` mutated (also its return value, for
    chaining)."""
    if granted_bytes is None or vp.transport != "shm":
        return vp
    budget = max(0, int(granted_bytes))
    kept: dict[int, int] = {}
    clamped = 0
    for size in sorted(vp.segment_sizes, reverse=True):
        want = vp.segment_sizes[size]
        fit = min(want, budget // size) if size > 0 else want
        if fit:
            kept[size] = fit
            budget -= size * fit
        clamped += (want - fit) * size
    vp.segment_sizes = kept
    vp.clamped_bytes = clamped
    return vp
