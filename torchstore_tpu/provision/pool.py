"""Client-local segment pool: pre-created staging for the direct path.

The volume-side warm pool (ShmServerCache.provision) covers buffered puts;
the DIRECT path's cold cost is different — the SOURCE process creates one
/dev/shm staging segment per shard at ``register`` time, on the critical
path of the first publish. This pool lets ``ts.prewarm(..., direct=True)``
pre-create and prefault those segments in the trainer's own process;
``DirectWeightSyncSource.register`` then draws exact-size segments instead
of allocating cold.

Process-local and advisory: ``take`` returning None simply means the lazy
path allocates as before.
"""

from __future__ import annotations

from typing import Optional

from torchstore_tpu.logging import get_logger

logger = get_logger("torchstore_tpu.provision.pool")


class LocalSegmentPool:
    def __init__(self) -> None:
        self._by_size: dict[int, list] = {}

    @property
    def pooled_bytes(self) -> int:
        return sum(
            size * len(segs) for size, segs in self._by_size.items()
        )

    def provision(
        self, sizes: dict[int, int], hugepages: bool = True, nthreads: int = 0
    ) -> dict:
        """Pre-create + prefault ``{size: count}`` segments (counting
        segments already pooled against the want). Synchronous — call it
        from an executor thread via the prewarm orchestrator."""
        from torchstore_tpu.transport import shared_memory as shm

        created = 0
        created_bytes = 0
        clamped_bytes = 0
        if not shm.is_available():
            return {"created": 0, "bytes": 0, "error": "shm unavailable"}
        # Clamp to HALF of tmpfs availability (minus a safety margin):
        # pre-faulting writes every page, and a write past tmpfs-full is
        # SIGBUS — fatal to the trainer process, not an exception the
        # advisory-prewarm contract could absorb. Unlike the volume legs,
        # client-local staging is NOT governed by the controller's
        # reservation (the trainer's host may not run a volume at all), so
        # the half-budget keeps two trainers booting simultaneously on one
        # host from jointly writing past the tmpfs; wider races stay
        # possible and are accepted — this leg is advisory, and a clamped
        # pool just means register() cold-creates the remainder lazily.
        budget = max(0, (shm.shm_available_bytes() - (256 << 20)) // 2)
        for size, count in sorted(sizes.items(), reverse=True):
            size = max(int(size), 1)
            want = max(0, int(count) - len(self._by_size.get(size, ())))
            fits = min(want, budget // size)
            budget -= fits * size
            clamped_bytes += (want - fits) * size
            for _ in range(fits):
                seg = shm.ShmSegment.create_provisioned(
                    size, hugepages=hugepages, nthreads=nthreads
                )
                self._by_size.setdefault(size, []).append(seg)
                created += 1
                created_bytes += size
        if clamped_bytes:
            logger.info(
                "local staging prewarm clamped %d bytes to tmpfs headroom",
                clamped_bytes,
            )
        return {
            "created": created,
            "bytes": created_bytes,
            "clamped_bytes": clamped_bytes,
        }

    def take(self, size: int):
        segs = self._by_size.get(max(int(size), 1))
        if not segs:
            return None
        return segs.pop()

    def clear(self) -> None:
        for segs in self._by_size.values():
            for seg in segs:
                seg.unlink()
        self._by_size.clear()


_pool: Optional[LocalSegmentPool] = None


def local_pool() -> LocalSegmentPool:
    global _pool
    if _pool is None:
        _pool = LocalSegmentPool()
    return _pool
