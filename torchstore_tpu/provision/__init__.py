"""Cold-start provisioning: manifest-driven prewarm of pools, transports,
and transfer plans.

The store's steady state is fast (segment-reuse handshakes, promoted bulk
connections, cached transfer plans) but the FIRST sync of a working set pays
every layer's lazy setup on its critical path: tmpfs segment allocation and
page faults, TCP dials, transfer-plan construction, device-transfer-server
startup. This subsystem provisions all of it ahead of time from a
**StateDictManifest** — keys, shapes, dtypes, shardings, total bytes —
derived from a live state dict (metadata only; no bytes move) or built by
hand before weights exist.

    planner      manifest + fleet topology -> per-volume segment/dial plan
                 (provision/planner.py, pure math)
    reservation  controller-arbitrated tmpfs capacity grants so concurrent
                 prewarms can't oversubscribe /dev/shm (controller.py)
    executors    pool pre-sizing with hugepage-backed, native-threaded
                 prefault (shared_memory / tsnative.cc), bulk pre-dial +
                 registration prewarm (bulk.py), ICI server start
                 (device_transfer.py), direct-path plan precompute
                 (direct_weight_sync.py)
    api          ``ts.prewarm(...)`` plus the automatic hint path in
                 ``put_state_dict`` / ``WeightPublisher.register``

Failure contract: prewarm is ADVISORY. Any stage failing logs, increments
``ts_prewarm_errors_total``, and the subsequent sync proceeds on the lazy
path unchanged.
"""

from torchstore_tpu.provision.executors import (
    as_manifest,
    maybe_auto_prewarm,
    prewarm_manifest,
)
from torchstore_tpu.provision.manifest import ManifestEntry, StateDictManifest
from torchstore_tpu.provision.planner import (
    ProvisionPlan,
    VolumePlan,
    clamp_to_grant,
    expected_bulk_conns,
    plan_provisioning,
)
from torchstore_tpu.provision.pool import LocalSegmentPool, local_pool

__all__ = [
    "LocalSegmentPool",
    "ManifestEntry",
    "ProvisionPlan",
    "StateDictManifest",
    "VolumePlan",
    "as_manifest",
    "clamp_to_grant",
    "expected_bulk_conns",
    "local_pool",
    "maybe_auto_prewarm",
    "plan_provisioning",
    "prewarm_manifest",
]
