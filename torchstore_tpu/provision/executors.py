"""Prewarm orchestrator: drive a ProvisionPlan through the live stack.

``prewarm_manifest`` is the engine behind ``ts.prewarm`` and the automatic
``put_state_dict`` hint path. Contract (ISSUE acceptance): it NEVER raises —
every stage failure (volume down, tmpfs full, dial refused) is logged,
counted in ``ts_prewarm_errors_total``, reported in the returned dict, and
the subsequent sync proceeds on the lazy path exactly as before.

Stages, each its own span under ``provision.prewarm``:

1. plan      — manifest + strategy fan-out + per-volume transport rung
2. reserve   — controller capacity reservation (concurrent prewarms can't
               oversubscribe tmpfs); grants clamp the plan
3. shm       — per-volume pool pre-sizing (hugepage + native prefault)
4. bulk      — connection pre-dial (+ stripe set) and registration prewarm
5. device    — ICI transfer-server start when the working set is on device
6. release   — drop the reservation (the pool itself now holds the bytes)
"""

from __future__ import annotations

import asyncio
import uuid
import weakref
from typing import Any, Optional

from torchstore_tpu.logging import get_logger
from torchstore_tpu.observability import context as obs_context
from torchstore_tpu.observability import metrics as obs_metrics
from torchstore_tpu.observability.tracing import span
from torchstore_tpu.provision import planner
from torchstore_tpu.provision.manifest import StateDictManifest

logger = get_logger("torchstore_tpu.provision")

_RUNS = obs_metrics.counter(
    "ts_prewarm_runs_total", "Prewarm invocations (explicit + auto-hint)"
)
_BYTES = obs_metrics.counter(
    "ts_prewarm_bytes_total",
    "Bytes pre-faulted into pools/staging by prewarm, by leg",
)
_SEGMENTS = obs_metrics.counter(
    "ts_prewarm_segments_total", "Segments pre-created by prewarm, by leg"
)
_DIALS = obs_metrics.counter(
    "ts_prewarm_dials_total", "Connections pre-opened by prewarm, by leg"
)
_ERRORS = obs_metrics.counter(
    "ts_prewarm_errors_total", "Prewarm stage failures (lazy path proceeded)"
)
_CLAMPED = obs_metrics.counter(
    "ts_prewarm_clamped_bytes_total",
    "Plan bytes dropped by capacity grants (tmpfs headroom)",
)


def _fail(report: dict, stage: str, exc: BaseException) -> None:
    _ERRORS.inc(stage=stage)
    report["ok"] = False
    report["errors"][stage] = f"{type(exc).__name__}: {exc}"
    logger.warning(
        "prewarm stage %s failed (%s: %s); lazy path will serve",
        stage,
        type(exc).__name__,
        exc,
    )


def as_manifest(
    state_dict_or_manifest: Any,
    transfer_dtype=None,
    transfer_quant: Optional[str] = None,
    quant_block: int = 256,
) -> StateDictManifest:
    if isinstance(state_dict_or_manifest, StateDictManifest):
        return state_dict_or_manifest
    return StateDictManifest.from_state_dict(
        state_dict_or_manifest,
        transfer_dtype=transfer_dtype,
        transfer_quant=transfer_quant,
        quant_block=quant_block,
    )


async def prewarm_manifest(
    client,
    manifest: StateDictManifest,
    direct: bool = False,
    arrays: Optional[list] = None,
) -> dict:
    """Provision every layer a sync of ``manifest`` will touch. Returns a
    report dict; never raises. ``direct=True`` additionally pre-creates the
    client-local staging segments a direct-source ``register`` will draw.
    ``arrays`` (optional, real source buffers) feed the bulk registration
    cache."""
    report: dict[str, Any] = {
        "ok": True,
        "manifest_bytes": manifest.total_bytes,
        "entries": len(manifest.entries),
        "planned_bytes": 0,
        "clamped_bytes": 0,
        "granted_bytes": {},
        "segments": 0,
        "bytes": 0,
        "dials": 0,
        "local_segments": 0,
        "device_server": None,
        "errors": {},
    }
    _RUNS.inc()
    try:
        with obs_context.ensure_root(), span(
            "provision.prewarm",
            nbytes=manifest.total_bytes,
            entries=len(manifest.entries),
        ):
            plan = await _build_plan(client, manifest, report)
            if plan is not None:
                reservation = await _reserve(client, plan, report)
                await _run_volume_legs(client, plan, report)
                if plan.device_server:
                    _run_device_leg(report)
                if reservation is not None:
                    try:
                        await client.controller.release_prewarm.call_one(
                            reservation
                        )
                    except Exception:  # noqa: BLE001 - TTL expires it anyway
                        pass
            if direct:
                await _run_local_staging_leg(client, manifest, report)
            if arrays:
                _run_registration_leg(client, plan, arrays, report)
    except Exception as exc:  # noqa: BLE001 - prewarm must never raise.
        # Exception, NOT BaseException: cancellation (the auto hint runs on
        # the put_state_dict path — a caller's wait_for timeout must still
        # cancel it) and interpreter exits propagate.
        _fail(report, "prewarm", exc)
    return report


async def _build_plan(client, manifest, report):
    try:
        with span("provision.plan", entries=len(manifest.entries)):
            await client._ensure_setup()
            strategy = client._strategy
            volume_ids = sorted(client._volume_refs or ())
            if not volume_ids:
                raise RuntimeError("no storage volumes")
            try:
                client_id = strategy.get_client_id()
            except Exception:  # noqa: BLE001 - strategy without env context
                client_id = volume_ids[0]
            put_ids = strategy.select_put_volume_ids(client_id, volume_ids)
            from torchstore_tpu.transport import device_transfer as dt
            from torchstore_tpu.transport.factory import create_transport_buffer

            transports = {
                vid: create_transport_buffer(
                    client._volume_refs[vid], client._config
                ).transport_name
                for vid in put_ids
            }
            plan = planner.plan_provisioning(
                manifest,
                put_ids,
                transports,
                ici_available=client._config.ici_enabled and dt.is_available(),
                arena_max_bytes=client._config.arena_max_bytes,
            )
            # Plan-cache handoff: hand the provisioned arena layout to the
            # client so even the FIRST put_state_dict of this working set
            # adopts it verbatim instead of re-deriving the packing.
            plan_cache = getattr(client, "plan_cache", None)
            if plan_cache is not None:
                hint = manifest.arena_hint(client._config.arena_max_bytes)
                if hint is not None:
                    plan_cache.seed(hint["sizes"], hint)
            report["transports"] = transports
            report["planned_bytes"] = plan.planned_bytes
            return plan
    except Exception as exc:  # noqa: BLE001 - cancellation propagates
        _fail(report, "plan", exc)
        return None


async def _reserve(client, plan, report) -> Optional[str]:
    asks = {
        vid: vp.planned_bytes
        for vid, vp in plan.volumes.items()
        if vp.transport == "shm" and vp.planned_bytes
    }
    if not asks:
        return None
    reservation = uuid.uuid4().hex
    try:
        with span("provision.reserve", volumes=len(asks)):
            result = await client.controller.reserve_prewarm.call_one(
                reservation, asks, config=client._config
            )
        grants = result.get("grants", {})
        report["granted_bytes"] = grants
        for vid, reason in (result.get("errors") or {}).items():
            _ERRORS.inc(stage="reserve")
            report["errors"][f"reserve:{vid}"] = reason
        for vid, vp in plan.volumes.items():
            planner.clamp_to_grant(vp, grants.get(vid))
        report["clamped_bytes"] = plan.clamped_bytes
        if plan.clamped_bytes:
            _CLAMPED.inc(plan.clamped_bytes)
            logger.info(
                "prewarm clamped %d bytes to fit capacity grants "
                "(tmpfs headroom)",
                plan.clamped_bytes,
            )
        return reservation
    except Exception as exc:  # noqa: BLE001 - proceed unclamped:
        # the volume-side provision clamps to its own pool cap regardless.
        _fail(report, "reserve", exc)
        return None


async def _run_volume_legs(client, plan, report) -> None:
    async def one(vid: str, vp) -> None:
        volume = client._volume_refs[vid]
        if vp.transport == "shm" and vp.segment_sizes:
            with span(
                "provision.shm", volume=vid, nbytes=vp.planned_bytes
            ):
                result = await volume.actor.provision_shm.call_one(
                    vp.segment_sizes, client._config
                )
            if result.get("error"):
                raise RuntimeError(f"volume {vid}: {result['error']}")
            report["segments"] += result.get("created", 0)
            report["bytes"] += result.get("bytes", 0)
            # The volume clamps to its own pool cap too (its config may be
            # stricter than the controller's grant) — surface both clamps.
            if result.get("clamped_bytes"):
                report["clamped_bytes"] += result["clamped_bytes"]
                _CLAMPED.inc(result["clamped_bytes"])
            _SEGMENTS.inc(result.get("created", 0), leg="shm")
            _BYTES.inc(result.get("bytes", 0), leg="shm")
            names = result.get("names") or []
            if names:
                # Client-side half of the SHM leg: attach the provisioned
                # segments NOW (populate=True) so the first put's offers hit
                # the attachment cache — page-table wiring off the hot path.
                from torchstore_tpu.transport import shared_memory as shm_mod

                with span(
                    "provision.pre_attach", volume=vid, segments=len(names)
                ):
                    # Await into a local FIRST: reading report[...] before
                    # the suspension would lose concurrent legs' updates
                    # under the multi-volume gather.
                    attached = await shm_mod.pre_attach_segments(volume, names)
                report["pre_attached"] = (
                    report.get("pre_attached", 0) + attached
                )
        elif vp.transport == "bulk" and vp.dials:
            from torchstore_tpu.transport import bulk

            with span("provision.bulk", volume=vid, dials=vp.dials):
                n = await bulk.prewarm_connection(
                    volume, client._config, stripes=vp.dials - 1
                )
            report["dials"] += n
            _DIALS.inc(n, leg="bulk")

    items = sorted(plan.volumes.items())
    results = await asyncio.gather(
        *(one(vid, vp) for vid, vp in items), return_exceptions=True
    )
    for (vid, _), result in zip(items, results):
        if isinstance(result, BaseException):
            if not isinstance(result, Exception):
                raise result  # cancellation: propagate, don't report
            _fail(report, f"volume:{vid}", result)


def _run_device_leg(report) -> None:
    try:
        from torchstore_tpu.transport import device_transfer as dt

        report["device_server"] = dt.prewarm_engine()
    except Exception as exc:  # noqa: BLE001
        _fail(report, "device", exc)


async def _run_local_staging_leg(client, manifest, report) -> None:
    """Pre-create the client-local staging segments a direct-source
    register() will draw (one exact-size segment per request). The creation
    + prefault runs on an executor thread — a model-scale prefault inline
    on the event loop would stall every concurrent RPC/sync."""
    try:
        from torchstore_tpu.provision.pool import local_pool

        config = getattr(client, "_config", None)
        loop = asyncio.get_running_loop()
        with span("provision.local_staging", nbytes=manifest.total_bytes):
            result = await loop.run_in_executor(
                None,
                lambda: local_pool().provision(
                    manifest.segment_sizes(),
                    hugepages=getattr(config, "prewarm_hugepages", True),
                    nthreads=getattr(config, "prewarm_threads", 0),
                ),
            )
        if result.get("error"):
            raise RuntimeError(result["error"])
        report["local_segments"] = result.get("created", 0)
        if result.get("clamped_bytes"):
            report["clamped_bytes"] += result["clamped_bytes"]
            _CLAMPED.inc(result["clamped_bytes"])
        _SEGMENTS.inc(result.get("created", 0), leg="local")
        _BYTES.inc(result.get("bytes", 0), leg="local")
    except Exception as exc:  # noqa: BLE001
        _fail(report, "local_staging", exc)


def _run_registration_leg(client, plan, arrays, report) -> None:
    try:
        from torchstore_tpu.transport import bulk

        registered = 0
        for vid, vp in (plan.volumes if plan is not None else {}).items():
            if vp.transport == "bulk":
                registered += bulk.prewarm_registrations(
                    client._volume_refs[vid], arrays
                )
        report["registrations"] = registered
    except Exception as exc:  # noqa: BLE001
        _fail(report, "registrations", exc)


# ---------------------------------------------------------------------------
# automatic hint path (put_state_dict)
# ---------------------------------------------------------------------------

# Per-client size-signatures already prewarmed this process lifetime: the
# hint fires once per distinct working-set shape, not once per publish.
# Weak client keys cannot survive a fork (children build fresh clients), so
# inherited entries are unreachable garbage at worst, never stale hits.
_auto_seen: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()  # tslint: disable=fork-safety


async def maybe_auto_prewarm(client, flat: dict) -> Optional[dict]:
    """The put_state_dict hint path: derive a manifest from the already-
    flattened dict and provision ahead of the first commit. Gated by
    ``config.prewarm_auto`` and ``prewarm_auto_min_bytes``; fires at most
    once per distinct size-signature per client; never raises."""
    try:
        config = getattr(client, "_config", None)
        if config is None or not getattr(config, "prewarm_auto", False):
            return None
        # Cheap pre-checks BEFORE any manifest construction: an RL loop
        # republishing the same working set every step must pay only this
        # signature computation on its critical path, not per-leaf manifest
        # derivation.
        signature = tuple(
            sorted(
                (key, int(nbytes))
                for key, value in flat.items()
                if isinstance((nbytes := getattr(value, "nbytes", 0)), int)
                and nbytes
            )
        )
        if sum(n for _, n in signature) < config.prewarm_auto_min_bytes:
            return None
        seen = _auto_seen.get(client)
        if seen is None:
            seen = _auto_seen[client] = set()
        if signature in seen:
            return None
        seen.add(signature)
        manifest = StateDictManifest.from_state_dict(flat)
        report = await prewarm_manifest(client, manifest)
        logger.info(
            "auto-prewarm: %d entries / %d bytes -> %d segment(s), "
            "%d dial(s)%s",
            report["entries"],
            report["manifest_bytes"],
            report["segments"],
            report["dials"],
            " (with errors)" if report["errors"] else "",
        )
        return report
    except Exception as exc:  # noqa: BLE001 - the put must proceed
        _fail(
            {"ok": False, "errors": {}},
            "auto",
            exc,
        )
        return None
