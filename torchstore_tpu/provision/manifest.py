"""StateDictManifest: the shape of a working set, without its bytes.

A manifest describes what a state-dict publish WILL put through the store —
per-flat-key shapes, dtypes, shardings (as per-request payload sizes) and the
total — derived purely from metadata: no device->host copies, no array
materialization. It is the planner's input (provision/planner.py) and the
picklable currency of ``ts.prewarm``: a trainer can derive it from a live
state dict, a ShapeDtypeStruct tree, or construct it by hand from a model
config before any weights exist at all.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from torchstore_tpu.transport.types import _np_dtype


@dataclass(frozen=True)
class ManifestEntry:
    """One flat state-dict leaf as the data plane will see it: the key,
    global shape/dtype, and the payload size of every put request the leaf
    decomposes into (one per addressable shard for mesh-sharded jax arrays,
    exactly one otherwise)."""

    key: str
    shape: tuple[int, ...]
    dtype: str
    # Bytes of each put-request payload this leaf expands to. Sums to the
    # leaf's (transfer-dtype-adjusted) nbytes.
    request_nbytes: tuple[int, ...]

    @property
    def nbytes(self) -> int:
        return sum(self.request_nbytes)


@dataclass
class StateDictManifest:
    """Keys, shapes, dtypes, shardings (as request sizes), and total bytes of
    a working set — everything the provisioning planner needs to size pools,
    dials, and transfer plans before the first byte moves."""

    entries: list[ManifestEntry] = field(default_factory=list)
    # True when any tensor leaf is a device-resident jax array: the ICI rung
    # (transfer server) is worth prewarming too.
    device_resident: bool = False
    # Flat keys in the SOURCE dict's insertion order — for a model state
    # dict this is model-forward order (flatten preserves dict iteration
    # order), the key order layer-streamed acquires consume layers in.
    # ``entries`` stays name-sorted for stable pool planning.
    order: tuple = ()

    @property
    def total_bytes(self) -> int:
        return sum(e.nbytes for e in self.entries)

    @property
    def key_order(self) -> list[str]:
        """Tensor-leaf flat keys in model-forward (insertion) order — the
        ``key_order`` argument of streamed acquires
        (``get_state_dict(stream=True, key_order=...)``,
        ``WeightSubscriber.acquire_streamed``)."""
        if self.order:
            named = {e.key for e in self.entries}
            return [k for k in self.order if k in named]
        return [e.key for e in self.entries]

    def segment_sizes(self, arena_max_bytes: int = 0) -> dict[int, int]:
        """{segment size: count} over every put request — exactly the pool
        the SHM transport's put handshake will ask the volume for (request
        payloads land in size-exact segments; empty tensors take the 1-byte
        minimum mapping).

        With ``arena_max_bytes`` > 0, requests at or below the threshold are
        packed the way the transport's small-key arena packs them (same
        layout function — ``transport.landing.compute_arena_layout``), so
        the provisioned pool holds ONE arena-sized segment instead of a
        thousand tiny ones the first put would never ask for."""
        sizes: dict[int, int] = {}
        small: list[int] = []
        for entry in self.entries:
            for nbytes in entry.request_nbytes:
                if 0 < arena_max_bytes and int(nbytes) <= arena_max_bytes:
                    small.append(int(nbytes))
                    continue
                size = max(int(nbytes), 1)
                sizes[size] = sizes.get(size, 0) + 1
        if len(small) >= 2:
            from torchstore_tpu.transport.landing import compute_arena_layout

            _, total = compute_arena_layout(small)
            sizes[total] = sizes.get(total, 0) + 1
        elif small:
            size = max(small[0], 1)
            sizes[size] = sizes.get(size, 0) + 1
        return sizes

    def arena_hint(self, arena_max_bytes: int) -> Optional[dict]:
        """The transport-shape arena layout for this manifest (plan-cache
        seed: ``ts.prewarm`` hands it to the client so even the FIRST
        put_state_dict adopts the provisioned layout verbatim)."""
        if arena_max_bytes <= 0:
            return None
        small = [
            int(n)
            for entry in self.entries
            for n in entry.request_nbytes
            if int(n) <= arena_max_bytes
        ]
        if len(small) < 2:
            return None
        from torchstore_tpu.transport.landing import compute_arena_layout

        offsets, total = compute_arena_layout(small)
        return {"sizes": tuple(small), "offsets": offsets, "total": total}

    def max_request_nbytes(self) -> int:
        return max(
            (n for e in self.entries for n in e.request_nbytes), default=0
        )

    @classmethod
    def from_state_dict(
        cls,
        state_dict: Any,
        transfer_dtype=None,
        transfer_quant: Optional[str] = None,
        quant_block: int = 256,
    ) -> "StateDictManifest":
        """Derive a manifest from a (possibly nested) state dict without
        moving any bytes. Tensor-ish leaves (numpy, torch, jax arrays and
        ShapeDtypeStructs, ``Shard`` wrappers) become entries; everything
        else (scalars, configs, opaque objects) is skipped — object puts ride
        the RPC codec and need no provisioning.

        ``transfer_quant`` sizes floating leaves as fused quant blobs
        (header + bitmap + packed codes + SCALE SLOT, via the shared
        ``landing.quant_wire_nbytes`` layout), so prewarmed pools hold
        exactly the scale-bearing arena segment a quantized first publish
        asks for."""
        from torchstore_tpu.state_dict_utils import flatten_state_dict

        if transfer_quant in (None, "none", ""):
            transfer_quant = None
        flat, _ = flatten_state_dict(state_dict)
        entries: list[ManifestEntry] = []
        device = False
        for key, value in sorted(flat.items()):
            entry, on_device = _entry_of(
                key, value, transfer_dtype, transfer_quant, quant_block
            )
            if entry is not None:
                entries.append(entry)
                device = device or on_device
        return cls(
            entries=entries,
            device_resident=device,
            order=tuple(flat),
        )


def _itemsize(dtype_name: str) -> int:
    try:
        return _np_dtype(dtype_name).itemsize
    except Exception:  # noqa: BLE001 - exotic dtype: assume 4 bytes
        return 4


def _is_floating_name(dtype_name: str) -> bool:
    if "bfloat16" in dtype_name:
        return True
    try:
        return np.issubdtype(np.dtype(dtype_name), np.floating)
    except TypeError:
        return "float" in dtype_name


def _transfer_itemsize(dtype_name: str, transfer_dtype) -> int:
    """Per-element wire size after the optional transfer-dtype cast (floating
    leaves only — ints/bools cross uncast, mirroring cast_floating_tensors)."""
    if transfer_dtype is not None and _is_floating_name(dtype_name):
        return _itemsize(str(np.dtype(transfer_dtype)))
    return _itemsize(dtype_name)


def _quant_entry(
    key: str,
    shape: tuple,
    dtype: str,
    transfer_quant: str,
    quant_block: int,
) -> ManifestEntry:
    """One floating leaf under wire quantization: a SINGLE fused-blob
    request (the blob is host-assembled whatever the source sharding),
    sized by the arena-layout module's quant_wire_nbytes so the scale slot
    is accounted for."""
    from torchstore_tpu.transport.landing import quant_wire_nbytes

    nelems = int(np.prod(shape)) if shape else 1
    block = quant_block if transfer_quant != "int8" else max(1, nelems)
    nbytes = quant_wire_nbytes(transfer_quant, block, nelems, len(shape))
    return ManifestEntry(key, shape, dtype, (nbytes,))


def _entry_of(
    key: str,
    value: Any,
    transfer_dtype,
    transfer_quant: Optional[str] = None,
    quant_block: int = 256,
) -> tuple[Optional[ManifestEntry], bool]:
    """(entry, is_device_resident) for one flat leaf; (None, False) for
    non-tensor leaves."""
    from torchstore_tpu import sharding as shd
    from torchstore_tpu import torch_interop
    from torchstore_tpu.client import Shard

    if transfer_quant is not None:
        entry, on_device = _entry_of(key, value, None)
        if entry is not None and _is_floating_name(entry.dtype):
            return (
                _quant_entry(
                    key, entry.shape, entry.dtype, transfer_quant, quant_block
                ),
                on_device,
            )
        return entry, on_device
    if isinstance(value, Shard):
        ts = value.tensor_slice
        shape = tuple(ts.local_shape)
        data = value.data
        dtype = str(data.dtype) if data is not None else "float32"
        itemsize = _transfer_itemsize(dtype, transfer_dtype)
        nbytes = int(np.prod(shape)) * itemsize if shape else itemsize
        return ManifestEntry(key, shape, dtype, (nbytes,)), False
    if isinstance(value, np.ndarray) or torch_interop.is_torch_tensor(value):
        shape = tuple(int(s) for s in value.shape)
        dtype = str(value.dtype).replace("torch.", "")
        itemsize = _transfer_itemsize(dtype, transfer_dtype)
        count = int(np.prod(shape)) if shape else 1
        return ManifestEntry(key, shape, dtype, (count * itemsize,)), False
    if (
        shd.is_jax_array(value)
        or shd.is_sharded_spec(value)
        or shd.is_plain_spec(value)
    ):
        shape = tuple(int(s) for s in value.shape)
        dtype = str(value.dtype)
        itemsize = _transfer_itemsize(dtype, transfer_dtype)
        on_device = shd.is_jax_array(value)
        sharding = getattr(value, "sharding", None)
        if sharding is None or shd._is_demotable(sharding):
            count = int(np.prod(shape)) if shape else 1
            return ManifestEntry(key, shape, dtype, (count * itemsize,)), on_device
        # Per-shard request sizes from the sharding's index map — the exact
        # decomposition sharding.put_requests will produce (one request per
        # addressable shard, replicated coordinates included), metadata-only.
        sizes: list[int] = []
        index_map = sharding.addressable_devices_indices_map(shape)
        for index in index_map.values():
            local = tuple(
                int((sl.stop if sl.stop is not None else dim) - (sl.start or 0))
                for sl, dim in zip(index, shape)
            )
            count = int(np.prod(local)) if local else 1
            sizes.append(count * itemsize)
        return ManifestEntry(key, shape, dtype, tuple(sizes)), on_device
    if hasattr(value, "__array_interface__"):
        arr = np.asarray(value)
        itemsize = _transfer_itemsize(str(arr.dtype), transfer_dtype)
        count = int(np.prod(arr.shape)) if arr.shape else 1
        return (
            ManifestEntry(key, tuple(arr.shape), str(arr.dtype), (count * itemsize,)),
            False,
        )
    return None, False
