"""North-star config benchmark: full Llama-3-8B state-dict weight sync.

Builds a host state dict with EXACTLY the reference north-star model's
tensor inventory (llama3-8b: 291 tensors, ~16 GB bf16) and measures the
trainer->consumer sync paths end to end:

  buffered   put_state_dict + zero-copy get_state_dict through a volume
  direct     registered staging publish + pull into destination buffers

Run:  python benchmarks/llama8b_sync.py [--dtype bfloat16] [--scale 1.0]

``--scale`` shrinks the hidden sizes for quick runs (1.0 = real 8B shapes).
Results are recorded in BASELINE.md.
"""

import argparse
import asyncio
import sys
import time

import numpy as np


def llama8b_state_dict(
    dtype: str, scale: float, model: str = "8b", layers: "int | None" = None
) -> dict:
    import ml_dtypes

    from torchstore_tpu.models.llama import LlamaConfig

    # The canonical geometries, not copies. 70B shard shapes with a reduced
    # layer count are the VERDICT r3 item 8 config (full 80 layers = 141 GB
    # bf16, ~3x too big for host + staging + dest on this machine).
    cfg = (
        LlamaConfig.llama3_70b() if model == "70b" else LlamaConfig.llama3_8b()
    )
    np_dtype = np.dtype(ml_dtypes.bfloat16 if dtype == "bfloat16" else dtype)
    h = max(64, int(cfg.hidden_size * scale) // 64 * 64)
    inter = max(128, int(cfg.intermediate_size * scale) // 64 * 64)
    vocab = max(256, int(cfg.vocab_size * scale) // 64 * 64)
    n_layers = cfg.num_layers if scale >= 1.0 else max(2, int(cfg.num_layers * scale))
    if layers is not None:
        n_layers = layers
    heads, kv_heads = cfg.num_heads, cfg.num_kv_heads
    head_dim = h // heads

    def t(*shape):
        # empty+fill: building 16 GB of random bf16 via rand().astype would
        # dominate setup time; content doesn't affect transfer speed.
        arr = np.empty(shape, np_dtype)
        arr.reshape(-1)[:1] = 1.0
        return arr

    sd = {
        "embed": t(vocab, h),
        "final_norm": t(h),
        "lm_head": t(h, vocab),
        "layers": {},
    }
    for i in range(n_layers):
        sd["layers"][str(i)] = {
            "attn_norm": t(h),
            "mlp_norm": t(h),
            "q_proj": t(h, heads * head_dim),
            "k_proj": t(h, kv_heads * head_dim),
            "v_proj": t(h, kv_heads * head_dim),
            "o_proj": t(heads * head_dim, h),
            "gate_proj": t(h, inter),
            "up_proj": t(h, inter),
            "down_proj": t(inter, h),
        }
    return sd


def count(sd):
    n, total = 0, 0
    stack = [sd]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        else:
            n += 1
            total += node.nbytes
    return n, total


async def run(
    dtype: str, scale: float, model: str = "8b", layers: "int | None" = None
) -> None:
    import torchstore_tpu as ts

    sd = llama8b_state_dict(dtype, scale, model, layers)
    n_tensors, total = count(sd)
    print(
        f"# llama{model}-shaped state dict: {n_tensors} tensors, "
        f"{total / 1e9:.2f} GB {dtype} (scale={scale}, layers={layers})",
        file=sys.stderr,
    )
    await ts.initialize(
        store_name="l8b", strategy=ts.SingletonStrategy(default_transport_type="shm")
    )
    try:
        # Buffered: put + zero-copy snapshot get (steady state by iter 2-3:
        # the segment-rotation pool converges, then puts run at memcpy
        # speed and gets are metadata-only).
        out = None
        for it in range(4):
            t0 = time.perf_counter()
            await ts.put_state_dict("w", sd, store_name="l8b")
            t1 = time.perf_counter()
            out = await ts.get_state_dict("w", store_name="l8b")
            t2 = time.perf_counter()
            # "delivered" counts logical bytes handed to each side (2N per
            # round trip) — zero-copy delivery is the measured advantage;
            # the physical per-direction rates are printed alongside so
            # nothing hides behind the definition.
            print(
                f"# buffered iter {it}: put {total/1e9/(t1-t0):.2f} GB/s "
                f"physical, zero-copy get {(t2-t1)*1e3:.0f} ms, "
                f"delivered {2*total/1e9/(t2-t0):.2f} GB/s",
                file=sys.stderr,
            )
        assert float(np.asarray(out["embed"]).reshape(-1)[0]) == 1.0

        # Direct with registered staging: publish + pull into dest buffers.
        import jax  # noqa: F401 - keep parity with bench env

        user = None
        await ts.put_state_dict("d", sd, direct=True, store_name="l8b")
        staging = ts.direct_staging_buffers("d", store_name="l8b")
        assert staging is not None

        def zeros_like_tree(node):
            if isinstance(node, dict):
                return {k: zeros_like_tree(v) for k, v in node.items()}
            return np.zeros_like(node)

        user = zeros_like_tree(sd)
        for it in range(4):
            t0 = time.perf_counter()
            await ts.put_state_dict("d", staging, direct=True, store_name="l8b")
            t1 = time.perf_counter()
            await ts.get_state_dict(
                "d", user_state_dict=user, direct=True, store_name="l8b"
            )
            t2 = time.perf_counter()
            print(
                f"# direct+registered iter {it}: publish {(t1-t0)*1e3:.0f} ms, "
                f"pull {total/1e9/(t2-t1):.2f} GB/s physical, "
                f"delivered {2*total/1e9/(t2-t0):.2f} GB/s",
                file=sys.stderr,
            )
        assert float(user["layers"]["0"]["q_proj"].reshape(-1)[0]) == 1.0
    finally:
        await ts.shutdown("l8b")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--model", choices=("8b", "70b"), default="8b")
    ap.add_argument("--layers", type=int, default=None,
                    help="override layer count (70b default run uses 8)")
    args = ap.parse_args()
    asyncio.run(run(args.dtype, args.scale, args.model, args.layers))
