"""Llama-3-70B-scale direct weight sync (VERDICT r3 item 8).

The llama8b harness run at REAL 70B shard shapes — hidden 8192,
intermediate 28672, 64 heads / 8 kv heads, 128256 vocab — with a reduced
layer count (default 8 of 80: the full model is ~141 GB bf16, ~3x too big
for source + registered staging + dest buffers on one host). Per-tensor
shapes, and therefore per-transfer behavior (segment sizes, plan shapes,
copy granularity), match the real model exactly; only the tensor COUNT is
reduced.

Run:  python benchmarks/llama70b_sync.py [--layers 8] [--dtype bfloat16]

Measures the buffered path and the direct + registered-staging path
(publish is copy-free; the pull moves each byte once). Results are
recorded in BASELINE.md.
"""

import argparse
import asyncio

from llama8b_sync import run

if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--layers", type=int, default=8)
    args = ap.parse_args()
    asyncio.run(run(args.dtype, 1.0, model="70b", layers=args.layers))
