"""Ring-over-sp vs dense attention at equal per-device sequence.

The VERDICT r3 item 5 comparison: on an sp-way mesh, ring attention
processes an sp-times LONGER global sequence while holding the same
per-device q/kv block sizes dense attention uses on one device — the
long-context trade the op exists for. Reports wall time, achieved
attention TFLOP/s, and the ring/dense ratio.

Run (real chip: drop the env forcing; CPU validation shown):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python benchmarks/ring_attention_bench.py --per-device-seq 1024

On hardware, results belong in BASELINE.md next to the dense-vs-pallas
numbers.
"""

import argparse
import statistics
import sys
import time


def attention_flops(b: int, sq: int, sk: int, h: int, d: int, causal: bool) -> float:
    """2 matmuls (scores + values), 2*m*n*k each; causal halves the work."""
    full = 2 * (2.0 * b * h * sq * sk * d)
    return full / 2 if causal else full


def run(per_device_seq: int, heads: int, head_dim: int, batch: int,
        causal: bool, impl: str) -> None:
    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # This image's sitecustomize overrides the env var with the TPU
        # tunnel platform (which hangs when the tunnel is down); honor an
        # explicit CPU request at config level.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchstore_tpu import parallel
    from torchstore_tpu.ops.ring_attention import ring_attention_sharded

    n_dev = len(jax.devices())
    global_seq = per_device_seq * n_dev
    dtype = jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
    keys = jax.random.split(jax.random.key(0), 3)

    def timed(fn, *args, iters=5):
        out = fn(*args)
        jax.block_until_ready(out)  # compile
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    # Dense baseline: ONE device's workload (per_device_seq x per_device_seq).
    q1 = jax.random.normal(keys[0], (batch, per_device_seq, heads, head_dim), dtype)
    dense_s = timed(
        jax.jit(lambda q, k, v: jax.nn.dot_product_attention(q, k, v, is_causal=causal)),
        q1, q1, q1,
    )
    dense_fl = attention_flops(batch, per_device_seq, per_device_seq, heads, head_dim, causal)
    print(
        f"# dense 1-device seq={per_device_seq}: {dense_s*1e3:.1f} ms, "
        f"{dense_fl/dense_s/1e12:.3f} TFLOP/s",
        file=sys.stderr,
    )

    # Ring: sp-way mesh, global_seq total, same per-device block size.
    mesh = parallel.make_mesh({"sp": n_dev})
    spec = NamedSharding(mesh, P(None, "sp", None, None))
    qg = jax.device_put(
        jax.random.normal(keys[1], (batch, global_seq, heads, head_dim), dtype), spec
    )
    ring_s = timed(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, "sp", causal=causal, impl=impl),
        qg, qg, qg,
    )
    ring_fl = attention_flops(batch, global_seq, global_seq, heads, head_dim, causal)
    per_dev_tfs = ring_fl / ring_s / 1e12 / n_dev
    print(
        f"# ring sp={n_dev} global_seq={global_seq} impl={impl}: "
        f"{ring_s*1e3:.1f} ms, {ring_fl/ring_s/1e12:.3f} TFLOP/s total "
        f"({per_dev_tfs:.3f}/device)",
        file=sys.stderr,
    )
    # Exactness spot check vs dense on the full sequence (host, fp32).
    if global_seq <= 4096:
        qh = np.asarray(qg, np.float32)
        ref = jax.nn.dot_product_attention(qh, qh, qh, is_causal=causal)
        got = np.asarray(
            ring_attention_sharded(qg, qg, qg, mesh, "sp", causal=causal, impl=impl),
            np.float32,
        )
        atol = 3e-2 if dtype == jnp.bfloat16 else 3e-5
        np.testing.assert_allclose(got, np.asarray(ref), atol=atol, rtol=atol)
        print("# exactness vs dense on the full sequence: OK", file=sys.stderr)
    print(
        f"# per-device efficiency vs 1-device dense: "
        f"{per_dev_tfs / (dense_fl/dense_s/1e12):.2f}x "
        "(>1 possible: causal ring skips cross-hop future blocks)",
        file=sys.stderr,
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--per-device-seq", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument(
        "--causal",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="causal mask (default); --no-causal benches the full-matrix mode",
    )
    ap.add_argument("--impl", default="auto", choices=("auto", "fused", "einsum"))
    args = ap.parse_args()
    run(args.per_device_seq, args.heads, args.head_dim, args.batch,
        args.causal, args.impl)
