"""Large-tensor transport sweep: put/get latency + GB/s across sizes and
transports, CSV output — the reference's benchmark machinery
(/root/reference/tests/test_large_tensors.py:38-104 generate_benchmark)
as a standalone harness. Run:

    python benchmarks/sweep.py [--sizes-mb 4,64,256] [--out sweep.csv]
"""

import argparse
import asyncio
import csv
import sys
import time

import numpy as np


async def run(sizes_mb: list[int], out_path: str) -> None:
    import torchstore_tpu as ts

    rows = []
    for transport in ("shm", "bulk", "rpc"):
        await ts.initialize(
            store_name="sweep",
            strategy=ts.SingletonStrategy(default_transport_type=transport),
        )
        try:
            for size_mb in sizes_mb:
                n = size_mb * 1024 * 1024 // 4
                x = np.random.rand(n).astype(np.float32)
                dest = np.zeros_like(x)
                # Steady state needs the segment-rotation cycle to converge
                # (put -> retire -> release -> pool): 3 warm iterations,
                # then report the best timed pair (standard steady-state
                # methodology; cold-start is bench.py's iter-0 line).
                best_put = best_get = float("inf")
                for it in range(4):
                    t0 = time.perf_counter()
                    await ts.put("k", x, store_name="sweep")
                    t1 = time.perf_counter()
                    await ts.get("k", like=dest, store_name="sweep")
                    t2 = time.perf_counter()
                    if it > 0:
                        best_put = min(best_put, t1 - t0)
                        best_get = min(best_get, t2 - t1)
                assert dest[0] == x[0]
                rows.append(
                    {
                        "transport": transport,
                        "size_mb": size_mb,
                        "put_s": round(best_put, 5),
                        "get_s": round(best_get, 5),
                        "put_gbps": round(x.nbytes / 1e9 / best_put, 3),
                        "get_gbps": round(x.nbytes / 1e9 / best_get, 3),
                    }
                )
                print(f"# {rows[-1]}", file=sys.stderr)
                await ts.delete("k", store_name="sweep")
        finally:
            await ts.shutdown("sweep")

    # Direct one-hop steady state for the largest size.
    size_mb = sizes_mb[-1]
    n = size_mb * 1024 * 1024 // 4
    sd = {"w": np.random.rand(n).astype(np.float32)}
    user = {"w": np.zeros(n, np.float32)}
    await ts.initialize(store_name="sweep")
    try:
        await ts.put_state_dict("d", sd, direct=True, store_name="sweep")
        await ts.get_state_dict("d", user_state_dict=user, direct=True, store_name="sweep")
        t0 = time.perf_counter()
        await ts.put_state_dict("d", sd, direct=True, store_name="sweep")
        t1 = time.perf_counter()
        await ts.get_state_dict("d", user_state_dict=user, direct=True, store_name="sweep")
        t2 = time.perf_counter()
        rows.append(
            {
                "transport": "direct",
                "size_mb": size_mb,
                "put_s": round(t1 - t0, 5),
                "get_s": round(t2 - t1, 5),
                "put_gbps": round(sd["w"].nbytes / 1e9 / (t1 - t0), 3),
                "get_gbps": round(sd["w"].nbytes / 1e9 / (t2 - t1), 3),
            }
        )
        print(f"# {rows[-1]}", file=sys.stderr)
    finally:
        await ts.shutdown("sweep")

    # Post-run CSV dump: the fleet is already shut down, nothing else shares
    # this loop. # tslint: disable=async-blocking
    with open(out_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)
    print(f"wrote {len(rows)} rows to {out_path}")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes-mb", default="4,64,256")
    parser.add_argument("--out", default="benchmarks/sweep.csv")
    args = parser.parse_args()
    sizes = [int(s) for s in args.sizes_mb.split(",")]
    asyncio.run(run(sizes, args.out))
