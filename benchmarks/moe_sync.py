"""Mixtral-8x7B expert-parallel weight sync benchmark (VERDICT r3 item 4).

Real 8x7B expert matrix shapes (hidden 4096, expert FFN 14336, 8 experts
per layer, 2 layers by default) exercised through the store's EP semantics:

- **push (dp x ep=8)**: each of 8 virtual ranks owns its expert's three FFN
  matrices per layer, published as PLAIN tensors under per-expert keys —
  the analog of the reference's fully-local DTensor demotion
  (/root/reference/torchstore/transport/types.py:58-85: Replicate/mesh-1
  expert weights store as plain tensors, one key per expert). Shared
  attention weights are published as 8-way TensorSlice shards.
- **pull (ep=4)**: a differently-shaped consumer fleet — each of 4 ranks
  pulls TWO whole experts (cross-rank whole-tensor gets) plus its 4-way
  reshard of the attention weights (each dest slice spans two source
  shards: a true reshard read).

All ranks run in one process (asyncio-concurrent) — the store and its
volume processes are the system under test, exactly like bench.py.

Run:  python benchmarks/moe_sync.py [--layers 2] [--dtype bfloat16]
      [--scale 1.0]

Results are recorded in BASELINE.md.
"""

import argparse
import asyncio
import sys
import time

import numpy as np

HIDDEN = 4096
EXPERT_FFN = 14336
N_EXPERTS = 8
N_HEADS = 32
N_KV_HEADS = 8
EP_PUSH = 8
EP_PULL = 4


def _np_dtype(dtype: str):
    if dtype == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(dtype)


def make_tensors(layers: int, dtype: str, scale: float):
    """(expert_weights, attn_weights): expert_weights[layer][expert] ->
    {w1, w2, w3}; attn_weights[layer] -> {q,k,v,o} full matrices."""
    dt = _np_dtype(dtype)
    h = max(64, int(HIDDEN * scale) // 64 * 64)
    ffn = max(128, int(EXPERT_FFN * scale) // 64 * 64)
    head_dim = h // N_HEADS

    def t(*shape):
        arr = np.empty(shape, dt)
        arr.reshape(-1)[:1] = 1.0
        return arr

    experts = [
        [
            {"w1": t(h, ffn), "w2": t(ffn, h), "w3": t(h, ffn)}
            for _ in range(N_EXPERTS)
        ]
        for _ in range(layers)
    ]
    attn = [
        {
            "q": t(h, N_HEADS * head_dim),
            "k": t(h, N_KV_HEADS * head_dim),
            "v": t(h, N_KV_HEADS * head_dim),
            "o": t(N_HEADS * head_dim, h),
        }
        for _ in range(layers)
    ]
    return experts, attn


def tree_bytes(node) -> int:
    if isinstance(node, dict):
        return sum(tree_bytes(v) for v in node.values())
    if isinstance(node, list):
        return sum(tree_bytes(v) for v in node)
    return node.nbytes


async def run(layers: int, dtype: str, scale: float) -> None:
    import torchstore_tpu as ts

    experts, attn = make_tensors(layers, dtype, scale)
    total = tree_bytes(experts) + tree_bytes(attn)
    print(
        f"# mixtral8x7b EP sync: {layers} layers, {N_EXPERTS} experts/layer, "
        f"{total / 1e9:.2f} GB {dtype} (scale={scale})",
        file=sys.stderr,
    )
    await ts.initialize(
        store_name="moe",
        strategy=ts.SingletonStrategy(default_transport_type="shm"),
    )
    try:

        def rank_push_items(rank: int) -> dict:
            """What source rank r publishes: its expert (fully-local plain
            tensors) + its attention shards (8-way dim-0 slices)."""
            items = {}
            for li in range(layers):
                ew = experts[li][rank]
                for name, arr in ew.items():
                    items[f"moe/l{li}/e{rank}/{name}"] = arr
                for name, full in attn[li].items():
                    rows = full.shape[0] // EP_PUSH
                    sl = ts.TensorSlice(
                        offsets=(rank * rows, 0),
                        local_shape=(rows, full.shape[1]),
                        global_shape=full.shape,
                        coordinates=(rank,),
                        mesh_shape=(EP_PUSH,),
                    )
                    items[f"moe/l{li}/attn/{name}"] = ts.Shard(
                        np.ascontiguousarray(full[rank * rows : (rank + 1) * rows]),
                        sl,
                    )
            return items

        def rank_pull_items(rank: int) -> dict:
            """What dest rank r (of EP_PULL) wants: TWO whole experts + its
            4-way attention reshard (spans two stored 8-way shards)."""
            per = N_EXPERTS // EP_PULL
            items = {}
            for li in range(layers):
                for e in range(rank * per, (rank + 1) * per):
                    for name in ("w1", "w2", "w3"):
                        items[f"moe/l{li}/e{e}/{name}"] = None
                for name, full in attn[li].items():
                    rows = full.shape[0] // EP_PULL
                    sl = ts.TensorSlice(
                        offsets=(rank * rows, 0),
                        local_shape=(rows, full.shape[1]),
                        global_shape=full.shape,
                        coordinates=(rank,),
                        mesh_shape=(EP_PULL,),
                    )
                    items[f"moe/l{li}/attn/{name}"] = ts.Shard(None, sl)
            return items

        push_sets = [rank_push_items(r) for r in range(EP_PUSH)]
        pull_sets = [rank_pull_items(r) for r in range(EP_PULL)]
        client = ts.client("moe")

        for it in range(4):
            stamp = float(it + 1)
            for items in push_sets:
                for v in items.values():
                    arr = v.data if isinstance(v, ts.Shard) else v
                    arr.reshape(-1)[:1] = stamp
            t0 = time.perf_counter()
            await asyncio.gather(
                *(client.put_batch(items) for items in push_sets)
            )
            t1 = time.perf_counter()
            outs = await asyncio.gather(
                *(client.get_batch(items) for items in pull_sets)
            )
            t2 = time.perf_counter()
            pulled = 0
            for out in outs:
                for v in out.values():
                    pulled += v.nbytes
            # Delivered: logical bytes handed to the store (total) + to the
            # consumers (pulled) per iteration; physical per-direction rates
            # alongside.
            print(
                f"# ep iter {it}: push {total/1e9/(t1-t0):.2f} GB/s physical"
                f", pull {pulled/1e9/(t2-t1):.2f} GB/s physical, delivered "
                f"{(total + pulled)/1e9/(t2-t0):.2f} GB/s",
                file=sys.stderr,
            )
            # Cross-layout verification: dest rank 1's first expert is
            # source rank 2's publication (layouts genuinely differ).
            probe = outs[1][f"moe/l0/e{N_EXPERTS // EP_PULL}/w1"]
            assert float(probe.reshape(-1)[0]) == stamp, "stale"
            for name in ("q", "k", "v", "o"):
                got = outs[0][f"moe/l0/attn/{name}"]
                want = attn[0][name][: got.shape[0]]
                assert got.shape == want.shape
        print("# verification: cross-layout expert + attention reshard OK", file=sys.stderr)
    finally:
        await ts.shutdown("moe")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--scale", type=float, default=1.0)
    args = ap.parse_args()
    asyncio.run(run(args.layers, args.dtype, args.scale))
