"""Achieved TFLOP/s of the attention kernels on the local device.

Times three implementations at the same shapes and prints TFLOP/s rows for
BASELINE.md (VERDICT r4 task 3):

- ``flash_attention_stats`` — the fused stats kernel (ring attention's
  per-hop production engine; XLA cannot emit its unnormalized acc/m/l)
- ``flash_attention`` — the normalized pallas twin (template / eager win)
- ``jax.nn.dot_product_attention`` — XLA's fused kernel (the model's dense
  path, models/llama.py)

FLOP accounting matches benchmarks/ring_attention_bench.py: 2 matmuls of
2*m*n*k each, halved when causal (the kernels skip fully-masked blocks).
Pass ``--peak-tflops`` (the chip's bf16 peak) to get an MFU%% column.

Run on hardware:  python benchmarks/flash_kernel_bench.py
CPU validation:   JAX_PLATFORMS=cpu python benchmarks/flash_kernel_bench.py \
                      --iters 2 --allow-interpret
(interpret-mode pallas on CPU is orders of magnitude slower — validation
checks the harness, not the numbers). Without a real device (platform
'tpu'/'axon' — the shared ``torchstore_tpu.utils.is_device_platform``
check, so the axon tunnel counts as hardware), the bench warns loudly and
exits nonzero unless ``--allow-interpret`` is passed: interpret-mode
TFLOP/s rows must never be mistaken for hardware numbers (ADVICE r5).
"""

import argparse
import statistics
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument(
        "--causal", action=argparse.BooleanOptionalAction, default=True
    )
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument(
        "--peak-tflops",
        type=float,
        default=None,
        help="chip bf16 peak for an MFU%% column (e.g. 197 for v5e)",
    )
    ap.add_argument(
        "--allow-interpret",
        action="store_true",
        help="proceed on CPU (pallas interpret mode) instead of exiting "
        "nonzero — harness validation only, the numbers are meaningless",
    )
    args = ap.parse_args()

    import os

    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # sitecustomize forces the TPU tunnel platform (hangs when the
        # tunnel is down); honor an explicit CPU request at config level.
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from torchstore_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_stats,
    )

    from torchstore_tpu.utils import is_device_platform

    dev = jax.devices()[0]
    on_device = is_device_platform(dev.platform)
    if not on_device:
        print(
            "#" * 72
            + f"\n# WARNING: no TPU (platform={dev.platform!r}) — pallas "
            "kernels would run\n# in INTERPRET mode; TFLOP/s rows would be "
            "meaningless as hardware numbers."
            + (
                "\n# Proceeding because --allow-interpret was passed "
                "(harness validation)."
                if args.allow_interpret
                else "\n# Refusing to emit them; pass --allow-interpret to "
                "validate the harness."
            )
            + "\n"
            + "#" * 72,
            file=sys.stderr,
        )
        if not args.allow_interpret:
            sys.exit(2)
    dtype = jnp.bfloat16 if on_device else jnp.float32
    b, s, h, d = args.batch, args.seq, args.heads, args.head_dim
    keys = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(keys[0], (b, s, h, d), dtype)
    k = jax.random.normal(keys[1], (b, s, h, d), dtype)
    v = jax.random.normal(keys[2], (b, s, h, d), dtype)
    full = 2 * (2.0 * b * h * s * s * d)
    flops = full / 2 if args.causal else full
    print(
        f"# device {dev.device_kind or dev.platform}, dtype {dtype.__name__}, "
        f"shape b{b} s{s} h{h} d{d}, causal={args.causal}",
        file=sys.stderr,
    )

    def timed(label, fn):
        out = fn()
        jax.block_until_ready(out)  # compile
        times = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            times.append(time.perf_counter() - t0)
        sec = statistics.median(times)
        tfs = flops / sec / 1e12
        mfu = (
            f", MFU {100 * tfs / args.peak_tflops:.0f}%"
            if args.peak_tflops
            else ""
        )
        print(f"# {label}: {sec*1e3:.3f} ms, {tfs:.1f} TFLOP/s{mfu}", file=sys.stderr)

    timed(
        "xla dot_product_attention (dense production path)",
        jax.jit(
            lambda: jax.nn.dot_product_attention(q, k, v, is_causal=args.causal)
        ),
    )
    timed(
        "pallas flash_attention (normalized)",
        lambda: flash_attention(q, k, v, causal=args.causal),
    )
    # The stats kernel's causal mode is the ring diagonal block
    # (block-local row>=col) — same masking cost as global causal here
    # because q and k cover the same range.
    timed(
        "pallas flash_attention_stats (ring per-hop engine)",
        lambda: flash_attention_stats(q, k, v, causal_diag=args.causal),
    )


if __name__ == "__main__":
    main()
